"""Device-side tree mutations on the postfix encoding.

Each function mirrors one tree-edit primitive of
/root/reference/src/MutationFunctions.jl, operating on a single unbatched
tree (fields ``[L]``) — callers vmap over candidates and speculative
attempts. Structural edits use the piece-concatenation gathers from
:mod:`.pieces`; value edits are masked writes. Every function returns
``(tree, ok)`` where ``ok=False`` marks a structurally impossible attempt
(e.g. result would exceed the slot budget), which the generation step
treats like a failed constraint check.

Randomness: mutation kernels take a flat uniform(0,1) slice ``u`` of a
statically-known budget (see :func:`branch_nu`) instead of a PRNG key —
the caller draws ONE bulk uniform tensor per generation step and hands
out slices, replacing ~1000 per-cycle small RNG device ops with one
(see evolve/rng.py). Key-based wrappers remain for the random tree
generators used at init time.
"""
# graftlint: assume-traced — pure device-kernel module; callers jit/vmap
# these functions from other modules, outside the module-local analysis.

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.encoding import (
    LEAF_CONST,
    LEAF_PARAM,
    LEAF_VAR,
    MAX_ARITY,
    TreeBatch,
    _tree_structure_single,
    lane_take,
)
from .pieces import combine_sources, concat_pieces, splice_span
from .rng import (
    USlice,
    u_bernoulli,
    u_categorical_weights,
    u_masked_choice,
    u_normal,
    u_randint,
)

__all__ = [
    "MutationContext",
    "branch_nu",
    "mutate_constant",
    "mutate_operator",
    "mutate_feature",
    "swap_operands",
    "rotate_tree",
    "add_node",
    "insert_node",
    "delete_node",
    "randomize_tree",
    "crossover_trees",
    "gen_random_tree",
    "gen_random_tree_fixed_size",
]


class MutationContext(NamedTuple):
    """Static + traced context shared by mutation kernels.

    ``nfeatures`` may be a *traced* scalar (template expressions mutate
    one subexpression at a time, each with its own argument count —
    get_nfeatures_for_mutation, /root/reference/src/TemplateExpression.jl:824-826);
    all kernels handle both. A (possibly traced) value of 0 forces
    constant leaves.
    """

    nops: Tuple[int, ...]  # static per-arity operator counts (1-based arity)
    nfeatures: "int | jax.Array"  # static int or traced scalar
    max_nodes: int         # static (L)
    perturbation_factor: float
    probability_negate_constant: float
    n_params: int = 0      # static; >0 => parametric leaf sampling
    # Route concat_pieces' int-field takes through a one-hot MXU matmul
    # instead of the where+masked-sum contraction. Wins ~3x per cycle at
    # small mutation batches (reference-scale configs) where XLA gives
    # the vmapped masked-sum a pathological layout; loses at the
    # bench-scale batches where the masked-sum lowering is already
    # efficient. Set from the static batch size in EvolveConfig.mctx.
    int_take_matmul: bool = False


_SCRATCH_NU = 4 * MAX_ARITY  # uniforms consumed by _make_leaf_scratch


def branch_nu(ctx: MutationContext) -> Dict[str, int]:
    """Uniform-slice budget of each mutation branch (static)."""
    L = ctx.max_nodes
    D = len(ctx.nops)
    S = _SCRATCH_NU
    return {
        "mutate_constant": L + 3,
        "mutate_operator": L + D,
        "mutate_feature": L + 1,
        "swap_operands": L,
        "rotate_tree": L + MAX_ARITY + 1,
        "add_node": 1 + (L + 2 * D + S) + (2 * D + 1 + S),
        "insert_node": L + 2 * D + 1 + S,
        "delete_node": L + 1,
        "randomize": 1 + 8 * L,
    }


def gen_tree_nu(ctx: MutationContext) -> int:
    """Uniform budget of gen_random_tree / gen_random_tree_fixed_size."""
    return 8 * ctx.max_nodes


def _assert_consumed(s: "USlice", u, what: str) -> None:
    """Trace-time check that a kernel consumed exactly its uniform budget
    (branch_nu drift would otherwise silently mis-slice the stream)."""
    assert s.i == u.shape[0], (
        f"{what} consumed {s.i} uniforms, budget is {u.shape[0]}"
    )


def _slot_mask(tree: TreeBatch):
    return jnp.arange(tree.arity.shape[0]) < tree.length


def _structure(tree: TreeBatch, structure=None):
    """(child, size, depth); pass a precomputed tuple to avoid re-deriving
    it in every mutation branch of a speculative attempt."""
    if structure is not None:
        return structure
    return _tree_structure_single(tree.arity, tree.length)


def _lane_get(x, k):
    """``x[k]`` for a [L] array and dynamic scalar ``k`` via lane_take's
    one-hot contraction. XLA lowers scalar dynamic-index gathers on TPU
    to serialized kCustom fusions — at the bench config the mutation
    kernels' scalar reads cost ~14 ms/cycle before this change
    (profiling/trace_machinery.py). Out-of-range ``k`` yields 0; every
    such read here is either index-valid by construction or fully
    masked downstream (same discard the clamped gather produced)."""
    return lane_take(x, jnp.asarray(k, jnp.int32).reshape(1))[0]


def _row_get(mat, k):
    """``mat[k, :]`` for an [L, A] array and dynamic scalar ``k`` — [A]."""
    return lane_take(mat.T, jnp.asarray(k, jnp.int32).reshape(1))[..., 0]


def _span(size, k):
    """(start, length) of the subtree rooted at slot k."""
    sz = _lane_get(size, k)
    return k - sz + 1, sz


# ---------------------------------------------------------------------------
# Value mutations
# ---------------------------------------------------------------------------


def _mutate_factor(u3, temperature, ctx: MutationContext, dtype):
    """Constant perturbation factor (src/MutationFunctions.jl:150-162).

    Note: the reference negates when ``rand() > probability_negate_constant``
    (:158), i.e. ~99% of the time with default 0.00743 — contradicting both
    the parameter's docstring and its name. We implement the documented
    semantics (negate *with* probability `probability_negate_constant`).
    """
    bottom = 0.1
    max_change = ctx.perturbation_factor * temperature + 1.0 + bottom
    factor = jnp.asarray(max_change, dtype) ** u3[0].astype(dtype)
    bigger = u_bernoulli(u3[1])
    factor = jnp.where(bigger, factor, 1.0 / factor)
    negate = u_bernoulli(u3[2], ctx.probability_negate_constant)
    return jnp.where(negate, -factor, factor)


def mutate_constant(u, tree: TreeBatch, temperature, ctx: MutationContext):
    s = USlice(u)
    mask = _slot_mask(tree) & (tree.arity == 0) & (tree.op == LEAF_CONST)
    idx, has_any = u_masked_choice(s.take(ctx.max_nodes), mask)
    factor = _mutate_factor(s.take(3), temperature, ctx, tree.const.dtype)
    _assert_consumed(s, u, "mutate_constant")
    new_const = tree.const.at[idx].multiply(factor)
    const = jnp.where(has_any, new_const, tree.const)
    return TreeBatch(tree.arity, tree.op, tree.feat, const, tree.length), jnp.bool_(True)


def mutate_parameter_row(u, params, temperature, ctx: MutationContext):
    """Scale one whole parameter row (all classes) by a mutate factor
    (parametric mutate_constant branch,
    /root/reference/src/ParametricExpression.jl:173-191).

    ``params``: [n_params, n_classes]; ``u``: [4] uniforms. No-op when
    there are no parameters.
    """
    if params.shape[-2] == 0:
        return params
    s = USlice(u)
    row = u_randint(s.take1(), params.shape[-2])
    factor = _mutate_factor(s.take(3), temperature, ctx, params.dtype)
    return params.at[row, :].multiply(factor)


def mutate_operator(u, tree: TreeBatch, ctx: MutationContext):
    s = USlice(u)
    mask = _slot_mask(tree) & (tree.arity > 0)
    idx, has_any = u_masked_choice(s.take(ctx.max_nodes), mask)
    u_ops = s.take(len(ctx.nops))
    _assert_consumed(s, u, "mutate_operator")
    a = _lane_get(tree.arity, idx)
    new_op = jnp.int32(0)
    for d, n in enumerate(ctx.nops, start=1):
        new_op = jnp.where(a == d, u_randint(u_ops[d - 1], max(n, 1)), new_op)
    op = jnp.where(has_any, tree.op.at[idx].set(new_op), tree.op)
    return TreeBatch(tree.arity, op, tree.feat, tree.const, tree.length), jnp.bool_(True)


def mutate_feature(u, tree: TreeBatch, ctx: MutationContext):
    s = USlice(u)
    mask = _slot_mask(tree) & (tree.arity == 0) & (tree.op == LEAF_VAR)
    idx, has_any = u_masked_choice(s.take(ctx.max_nodes), mask)
    u_delta = s.take1()
    _assert_consumed(s, u, "mutate_feature")
    if isinstance(ctx.nfeatures, int) and ctx.nfeatures <= 1:
        return tree, jnp.bool_(True)
    nf = jnp.asarray(ctx.nfeatures, jnp.int32)
    # uniform among features != current (src/MutationFunctions.jl:181)
    delta = u_randint(u_delta, jnp.maximum(nf - 1, 1)) + 1
    new_feat = (_lane_get(tree.feat, idx) + delta) % jnp.maximum(nf, 1)
    changed = has_any & (nf > 1)
    feat = jnp.where(changed, tree.feat.at[idx].set(new_feat), tree.feat)
    return TreeBatch(tree.arity, tree.op, feat, tree.const, tree.length), jnp.bool_(True)


# ---------------------------------------------------------------------------
# Structural mutations
# ---------------------------------------------------------------------------


def swap_operands(u, tree: TreeBatch, ctx: MutationContext, structure=None):
    """Swap the two child spans of a random binary node (:83-96)."""
    L = ctx.max_nodes
    child, size, _ = _structure(tree, structure)
    mask = _slot_mask(tree) & (tree.arity == 2)
    k_node, has_any = u_masked_choice(u, mask)
    crow = _row_get(child, k_node)
    c1 = crow[0]
    c2 = crow[1]
    s1, l1 = _span(size, c1)
    s2, l2 = _span(size, c2)
    sources = (tree.arity, tree.op, tree.feat, tree.const)
    starts = jnp.stack([jnp.int32(0), s2, s1, k_node, k_node + 1])
    lens = jnp.stack([s1, l2, l1, jnp.int32(1), tree.length - (k_node + 1)])
    new_tree, ok = concat_pieces(sources, starts, lens, L,
                                 int_matmul=ctx.int_take_matmul)
    return _select_tree(has_any, new_tree, tree), ok | ~has_any


def delete_node(u, tree: TreeBatch, ctx: MutationContext, structure=None):
    """Splice out a random operator node, keeping one child (:336-356)."""
    L = ctx.max_nodes
    s = USlice(u)
    child, size, _ = _structure(tree, structure)
    mask = _slot_mask(tree) & (tree.arity > 0)
    k_node, has_any = u_masked_choice(s.take(L), mask)
    carry_i = u_randint(s.take1(), jnp.maximum(_lane_get(tree.arity, k_node), 1))
    _assert_consumed(s, u, "delete_node")
    carry = _lane_get(_row_get(child, k_node),
                      jnp.clip(carry_i, 0, MAX_ARITY - 1))
    node_start, node_len = _span(size, k_node)
    carry_start, carry_len = _span(size, carry)
    sources = (tree.arity, tree.op, tree.feat, tree.const)
    new_tree, ok = splice_span(
        tree, node_start, k_node, sources, carry_start, carry_len, L,
        int_matmul=ctx.int_take_matmul,
    )
    return _select_tree(has_any, new_tree, tree), ok | ~has_any


def _sample_leaf(u4, ctx: MutationContext, dtype):
    """(op_code, feat, const) of one random leaf from 4 uniforms.

    Non-parametric: 50/50 constant ~ randn / variable ~ uniform feature
    (src/MutationFunctions.jl:321-333). Parametric (n_params > 0): uniform
    thirds constant / variable / parameter
    (make_random_leaf for ParametricNode,
    /root/reference/src/ParametricExpression.jl:113-137).
    """
    val = u_normal(u4[1]).astype(dtype)
    nf = jnp.asarray(ctx.nfeatures, jnp.int32)
    f = u_randint(u4[2], jnp.maximum(nf, 1))
    if ctx.n_params > 0:
        choice = u_randint(u4[0], 3)
        p = u_randint(u4[3], ctx.n_params)
        is_const = (choice == 0) | (nf <= 0)
        code = jnp.where(
            is_const, LEAF_CONST, jnp.where(choice == 1, LEAF_VAR, LEAF_PARAM)
        )
        feat = jnp.where(is_const, 0, jnp.where(choice == 1, f, p))
    else:
        is_const = u_bernoulli(u4[0]) | (nf <= 0)
        code = jnp.where(is_const, LEAF_CONST, LEAF_VAR)
        feat = jnp.where(is_const, 0, f)
    return code, feat, jnp.where(is_const, val, jnp.zeros((), dtype))


def _make_leaf_scratch(u, ctx: MutationContext, dtype):
    """Scratch arrays holding MAX_ARITY random leaves + one op slot from
    ``_SCRATCH_NU`` uniforms.

    Layout: slots [0..MAX_ARITY-1] are random leaves (_sample_leaf); slot
    MAX_ARITY is reserved for a new operator node written by callers.
    """
    S = MAX_ARITY + 1
    arity = jnp.zeros((S,), jnp.int32)
    op = jnp.zeros((S,), jnp.int32)
    feat = jnp.zeros((S,), jnp.int32)
    const = jnp.zeros((S,), dtype)
    for j in range(MAX_ARITY):
        code, fj, cj = _sample_leaf(u[4 * j:4 * j + 4], ctx, dtype)
        op = op.at[j].set(code)
        feat = feat.at[j].set(fj)
        const = const.at[j].set(cj)
    return arity, op, feat, const


def _sample_new_op(u, ctx: MutationContext, limit_arity=None):
    """Sample (arity, op_index) proportional to per-arity op counts
    (the csum draw at src/MutationFunctions.jl:209-221) from ``2 * D``
    uniforms."""
    D = len(ctx.nops)
    s = USlice(u)
    weights = jnp.asarray(ctx.nops, jnp.float32)
    if limit_arity is not None:
        weights = jnp.where(jnp.arange(1, D + 1) <= limit_arity, weights, 0.0)
    total = jnp.sum(weights)
    a = u_categorical_weights(s.take(D), weights) + 1
    u_ops = s.take(D)
    o = jnp.int32(0)
    for d, n in enumerate(ctx.nops, start=1):
        o = jnp.where(a == d, u_randint(u_ops[d - 1], max(n, 1)), o)
    return a.astype(jnp.int32), o, total > 0


def _expand_leaf_pieces(tree, scratch, k_node, node_start, node_len, new_arity,
                        carry_slot, ctx):
    """Pieces for replacing span [node_start, node_start+node_len) with a new
    operator node whose children are scratch leaves except `carry_slot`,
    which carries the original span. carry_slot=-1 means no carry (the
    original span is dropped — used by append where the target is a leaf).
    """
    L = ctx.max_nodes
    sources = combine_sources(
        tree,
        TreeBatch(scratch[0], scratch[1], scratch[2], scratch[3],
                  jnp.int32(MAX_ARITY + 1)),
    )
    starts = [jnp.int32(0)]
    lens = [node_start]
    for j in range(MAX_ARITY):
        in_use = j < new_arity
        is_carry = j == carry_slot
        starts.append(jnp.where(is_carry, node_start, L + j))
        lens.append(jnp.where(in_use, jnp.where(is_carry, node_len, 1), 0))
    # the new operator node (scratch slot MAX_ARITY)
    starts.append(jnp.int32(L + MAX_ARITY))
    lens.append(jnp.int32(1))
    # suffix
    starts.append(node_start + node_len)
    lens.append(tree.length - (node_start + node_len))
    return concat_pieces(sources, jnp.stack(starts), jnp.stack(lens), L,
                         int_matmul=ctx.int_take_matmul)


def _write_op_slot(scratch, a, o):
    arity, op, feat, const = scratch
    arity = arity.at[MAX_ARITY].set(a)
    op = op.at[MAX_ARITY].set(o)
    return arity, op, feat, const


def add_node(u, tree: TreeBatch, ctx: MutationContext, structure=None):
    """append/prepend a random op, 50/50 (src/Mutate.jl:479-497)."""
    L, D = ctx.max_nodes, len(ctx.nops)
    s = USlice(u)
    do_append = u_bernoulli(s.take1())
    appended, ok_a = append_random_op(
        s.take(L + 2 * D + _SCRATCH_NU), tree, ctx, structure
    )
    prepended, ok_p = prepend_random_op(
        s.take(2 * D + 1 + _SCRATCH_NU), tree, ctx
    )
    _assert_consumed(s, u, "add_node")
    out = _select_tree(do_append, appended, prepended)
    return out, jnp.where(do_append, ok_a, ok_p)


def append_random_op(u, tree: TreeBatch, ctx: MutationContext, structure=None):
    """Replace a random leaf with op(random leaves) (:199-226)."""
    L, D = ctx.max_nodes, len(ctx.nops)
    s = USlice(u)
    child, size, _ = _structure(tree, structure)
    mask = _slot_mask(tree) & (tree.arity == 0)
    k_leaf, has_any = u_masked_choice(s.take(L), mask)
    a, o, any_op = _sample_new_op(s.take(2 * D), ctx)
    scratch = _make_leaf_scratch(s.take(_SCRATCH_NU), ctx, tree.const.dtype)
    _assert_consumed(s, u, "append_random_op")
    scratch = _write_op_slot(scratch, a, o)
    new_tree, ok = _expand_leaf_pieces(
        tree, scratch, k_leaf, k_leaf, jnp.int32(1), a, jnp.int32(-1), ctx
    )
    valid = has_any & any_op
    return _select_tree(valid, new_tree, tree), ok | ~valid


def insert_random_op(u, tree: TreeBatch, ctx: MutationContext, structure=None):
    """Wrap a random node inside a new op (:243-272)."""
    L, D = ctx.max_nodes, len(ctx.nops)
    s = USlice(u)
    child, size, _ = _structure(tree, structure)
    mask = _slot_mask(tree)
    k_node, has_any = u_masked_choice(s.take(L), mask)
    a, o, any_op = _sample_new_op(s.take(2 * D), ctx)
    carry = u_randint(s.take1(), jnp.maximum(a, 1))
    scratch = _make_leaf_scratch(s.take(_SCRATCH_NU), ctx, tree.const.dtype)
    _assert_consumed(s, u, "insert_random_op")
    scratch = _write_op_slot(scratch, a, o)
    node_start, node_len = _span(size, k_node)
    new_tree, ok = _expand_leaf_pieces(
        tree, scratch, k_node, node_start, node_len, a, carry, ctx
    )
    valid = has_any & any_op
    return _select_tree(valid, new_tree, tree), ok | ~valid


def prepend_random_op(u, tree: TreeBatch, ctx: MutationContext):
    """New root with the old tree as a random child (:289-319)."""
    D = len(ctx.nops)
    s = USlice(u)
    a, o, any_op = _sample_new_op(s.take(2 * D), ctx)
    carry = u_randint(s.take1(), jnp.maximum(a, 1))
    scratch = _make_leaf_scratch(s.take(_SCRATCH_NU), ctx, tree.const.dtype)
    _assert_consumed(s, u, "prepend_random_op")
    scratch = _write_op_slot(scratch, a, o)
    new_tree, ok = _expand_leaf_pieces(
        tree, scratch, tree.length - 1, jnp.int32(0), tree.length, a, carry, ctx
    )
    return _select_tree(any_op, new_tree, tree), ok | ~any_op


def rotate_tree(u, tree: TreeBatch, ctx: MutationContext, structure=None):
    """AVL-style random rotation (randomly_rotate_tree!, :594-633).

    Chooses a rotation root R (an operator node with at least one operator
    child), a pivot P (operator child of R) and grandchild G (child of P);
    the rotated subtree is P with G's slot replaced by R' = R with P's slot
    replaced by G. Node count is preserved, so this is a permutation of
    spans — implemented as a 9-piece gather.
    """
    L = ctx.max_nodes
    s = USlice(u)
    child, size, _ = _structure(tree, structure)
    slot_ok = _slot_mask(tree)
    # lane_take, not a [L, A] gather: the serialized kCustom lowering of
    # this single line cost ~5 ms/cycle at the bench config.
    child_arity = lane_take(tree.arity, jnp.clip(child, 0, L - 1))  # [L, A]
    has_op_child = jnp.any(
        (child_arity > 0) & (jnp.arange(MAX_ARITY) < tree.arity[:, None]), axis=1
    )
    root_mask = slot_ok & (tree.arity > 0) & has_op_child
    r, has_root = u_masked_choice(s.take(L), root_mask)

    arity_r = _lane_get(tree.arity, r)
    pivot_mask = ((jnp.arange(MAX_ARITY) < arity_r)
                  & (_row_get(child_arity, r) > 0))
    pi, _ = u_masked_choice(s.take(MAX_ARITY), pivot_mask)
    row_r = _row_get(child, r)
    p = _lane_get(row_r, pi)
    arity_p = _lane_get(tree.arity, p)
    gi = u_randint(s.take1(), jnp.maximum(arity_p, 1))
    _assert_consumed(s, u, "rotate_tree")
    row_p = _row_get(child, p)
    g = _lane_get(row_p, jnp.clip(gi, 0, MAX_ARITY - 1))

    def span_of(x):
        sz = _lane_get(size, x)
        return x - sz + 1, sz

    g_start, g_len = span_of(g)
    # R' pieces: R's children in order with pivot slot -> G span; then R.
    rp_starts, rp_lens = [], []
    for i in range(MAX_ARITY):
        in_use = i < arity_r
        ci = row_r[i]
        ci_start, ci_len = span_of(ci)
        st = jnp.where(i == pi, g_start, ci_start)
        ln = jnp.where(i == pi, g_len, ci_len)
        rp_starts.append(jnp.where(in_use, st, 0))
        rp_lens.append(jnp.where(in_use, ln, 0))
    rp_starts.append(r)
    rp_lens.append(jnp.int32(1))

    # P' pieces: P's children in order, with G's slot -> the 3 R' pieces.
    starts, lens = [], []
    span_start, span_len = span_of(r)
    starts.append(jnp.int32(0))
    lens.append(span_start)
    for j in range(MAX_ARITY):
        in_use = j < arity_p
        cj = row_p[j]
        cj_start, cj_len = span_of(cj)
        is_g = j == gi
        # three sub-pieces: either the R' triple, or (child span, 0, 0)
        starts.append(jnp.where(is_g, rp_starts[0], cj_start))
        lens.append(jnp.where(in_use, jnp.where(is_g, rp_lens[0], cj_len), 0))
        starts.append(jnp.where(is_g, rp_starts[1], 0))
        lens.append(jnp.where(in_use & is_g, rp_lens[1], 0))
        starts.append(jnp.where(is_g, rp_starts[2], 0))
        lens.append(jnp.where(in_use & is_g, rp_lens[2], 0))
    starts.append(p)
    lens.append(jnp.int32(1))
    starts.append(span_start + span_len)
    lens.append(tree.length - (span_start + span_len))

    sources = (tree.arity, tree.op, tree.feat, tree.const)
    new_tree, ok = concat_pieces(sources, jnp.stack(starts), jnp.stack(lens), L,
                                 int_matmul=ctx.int_take_matmul)
    return _select_tree(has_root, new_tree, tree), ok | ~has_root


def crossover_trees(u, tree1: TreeBatch, tree2: TreeBatch, ctx: MutationContext,
                    structure1=None, structure2=None):
    """Random subtree exchange (crossover_trees, :488-518). ``u``: [2L]."""
    L = ctx.max_nodes
    s = USlice(u)
    _, size1, _ = _structure(tree1, structure1)
    _, size2, _ = _structure(tree2, structure2)
    n1, _ = u_masked_choice(s.take(L), _slot_mask(tree1))
    n2, _ = u_masked_choice(s.take(L), _slot_mask(tree2))
    _assert_consumed(s, u, "crossover_trees")
    s1, l1 = _span(size1, n1)
    s2, l2 = _span(size2, n2)
    sources12 = combine_sources(tree1, tree2)
    child1, ok1 = splice_span(tree1, s1, n1, sources12, L + s2, l2, L,
                              int_matmul=ctx.int_take_matmul)
    sources21 = combine_sources(tree2, tree1)
    child2, ok2 = splice_span(tree2, s2, n2, sources21, L + s1, l1, L,
                              int_matmul=ctx.int_take_matmul)
    return child1, child2, ok1, ok2


# ---------------------------------------------------------------------------
# Random tree generation
# ---------------------------------------------------------------------------


def _make_single_leaf_u(u4, ctx: MutationContext, dtype):
    code, f0, c0 = _sample_leaf(u4, ctx, dtype)
    L = ctx.max_nodes
    return TreeBatch(
        arity=jnp.zeros((L,), jnp.int32),
        op=jnp.zeros((L,), jnp.int32).at[0].set(code),
        feat=jnp.zeros((L,), jnp.int32).at[0].set(f0),
        const=jnp.zeros((L,), dtype).at[0].set(c0),
        length=jnp.int32(1),
    )


def _random_postfix_from_counts(u, n_binary, n_unary, ctx: MutationContext,
                                dtype):
    """Uniform random postfix tree with the given operator-arity counts.

    ``u``: [7L] uniforms. Loop-free construction (the reference grows
    trees by sequential leaf expansion, src/MutationFunctions.jl:441-471;
    a sequential loop is poison on TPU, so we sample the tree *shape*
    directly):

    1. lay out the arity multiset (``n_binary`` 2s, ``n_unary`` 1s,
       ``n_binary + 1`` 0s) and shuffle it with a masked argsort;
    2. rotate it into the unique valid postfix order via the cycle lemma
       (Dvoretzky–Motzkin: steps ``1 - arity`` sum to 1, so exactly one
       cyclic rotation keeps every prefix sum positive — start right
       after the last prefix-sum minimum);
    3. fill operator indices / leaf payloads with vectorized draws.

    This samples uniformly over tree shapes with the given op counts —
    a (documented) distributional delta from the reference's growth
    process, which biases toward unbalanced shapes.
    """
    L = ctx.max_nodes
    s = USlice(u)
    slot = jnp.arange(L, dtype=jnp.int32)
    m = 2 * n_binary + n_unary + 1        # total nodes (traced scalar)
    live = slot < m

    vals = jnp.where(
        slot < n_binary, 2, jnp.where(slot < n_binary + n_unary, 1, 0)
    ).astype(jnp.int32)
    prio = jnp.where(live, s.take(L), 2.0)
    perm = jnp.argsort(prio)
    arity = jnp.where(live, lane_take(vals, perm), 0)

    # cycle-lemma rotation (dead slots get +inf so they never win the min)
    S = jnp.cumsum(1 - arity)
    S_masked = jnp.where(live, S, jnp.iinfo(jnp.int32).max)
    minS = jnp.min(S_masked)
    t = jnp.max(jnp.where(S_masked == minS, slot, -1))   # last argmin
    p = jnp.where(t + 1 >= m, 0, t + 1)
    src = jnp.where(live, (p + slot) % jnp.maximum(m, 1), slot)
    arity = jnp.where(live, lane_take(arity, src), 0)

    # operator indices per arity
    nuna = ctx.nops[0] if len(ctx.nops) >= 1 else 0
    nbin = ctx.nops[1] if len(ctx.nops) >= 2 else 0
    op_u = u_randint(s.take(L), max(nuna, 1))
    op_b = u_randint(s.take(L), max(nbin, 1))

    # leaf payloads (vectorized _sample_leaf semantics)
    nf = jnp.asarray(ctx.nfeatures, jnp.int32)
    u_choice = s.take(L)
    const_vals = u_normal(s.take(L)).astype(dtype)
    feat_vals = u_randint(s.take(L), jnp.maximum(nf, 1))
    u_param = s.take(L)
    if ctx.n_params > 0:
        choice = u_randint(u_choice, 3)
        p_vals = u_randint(u_param, ctx.n_params)
        is_const = (choice == 0) | (nf <= 0)
        leaf_code = jnp.where(
            is_const, LEAF_CONST, jnp.where(choice == 1, LEAF_VAR, LEAF_PARAM)
        )
        leaf_feat = jnp.where(is_const, 0,
                              jnp.where(choice == 1, feat_vals, p_vals))
    else:
        is_const = (u_choice < 0.5) | (nf <= 0)
        leaf_code = jnp.where(is_const, LEAF_CONST, LEAF_VAR)
        leaf_feat = jnp.where(is_const, 0, feat_vals)

    op = jnp.where(
        arity == 2, op_b, jnp.where(arity == 1, op_u, leaf_code)
    ).astype(jnp.int32)
    feat = jnp.where((arity == 0) & live, leaf_feat, 0).astype(jnp.int32)
    const = jnp.where(
        (arity == 0) & live & is_const, const_vals, jnp.zeros((), dtype)
    )
    return TreeBatch(arity=arity, op=op, feat=feat, const=const,
                     length=m.astype(jnp.int32))


def _sample_arity_counts(u_L, budget, ctx: MutationContext):
    """(n_binary, n_unary) from iid arity draws filling ``budget`` size
    increments (binary costs 2, unary 1), matching the reference growth
    loop's weighted arity sampling in aggregate. ``u_L``: [L] uniforms."""
    nuna = ctx.nops[0] if len(ctx.nops) >= 1 else 0
    nbin = ctx.nops[1] if len(ctx.nops) >= 2 else 0
    if nbin == 0 and nuna == 0:
        z = jnp.zeros((), jnp.int32)
        return z, z
    pb = nbin / max(nbin + nuna, 1)
    draw_bin = u_L < pb
    if nuna == 0:
        draw_bin = jnp.ones_like(draw_bin)
    if nbin == 0:
        draw_bin = jnp.zeros_like(draw_bin)
    cost = jnp.where(draw_bin, 2, 1).astype(jnp.int32)
    csum = jnp.cumsum(cost)
    take = csum <= budget
    n_binary = jnp.sum(take & draw_bin).astype(jnp.int32)
    n_unary = jnp.sum(take & ~draw_bin).astype(jnp.int32)
    if nuna > 0:
        # fill a leftover single size unit with one unary op
        total = jnp.max(jnp.where(take, csum, 0))
        n_unary = n_unary + jnp.where(budget - total >= 1, 1, 0)
    return n_binary, n_unary


def _gen_random_tree_fixed_size_u(u, node_count, ctx: MutationContext, dtype):
    """u: [8L] uniforms."""
    s = USlice(u)
    budget = jnp.clip(node_count, 1, ctx.max_nodes) - 1
    n_binary, n_unary = _sample_arity_counts(s.take(ctx.max_nodes), budget, ctx)
    return _random_postfix_from_counts(
        s.take(7 * ctx.max_nodes), n_binary, n_unary, ctx, dtype
    )


def gen_random_tree_fixed_size(key, node_count, ctx: MutationContext, dtype,
                               n_steps=None):
    """Random tree of ~``node_count`` nodes
    (gen_random_tree_fixed_size, src/MutationFunctions.jl:441-471)."""
    del n_steps  # legacy knob of the sequential-growth implementation
    u = jax.random.uniform(key, (gen_tree_nu(ctx),))
    return _gen_random_tree_fixed_size_u(u, node_count, ctx, dtype)


def gen_random_tree(key, nlength, ctx: MutationContext, dtype):
    """Random tree from ``nlength`` weighted op draws (gen_random_tree,
    :384-398 appends `nlength` ops; sizes land in [nlength+1, 2*nlength+1])."""
    L = ctx.max_nodes
    u = jax.random.uniform(key, (8 * L,))
    s = USlice(u)
    nuna = ctx.nops[0] if len(ctx.nops) >= 1 else 0
    nbin = ctx.nops[1] if len(ctx.nops) >= 2 else 0
    if nbin == 0 and nuna == 0:
        return _make_single_leaf_u(s.take(4), ctx, dtype)
    pb = nbin / max(nbin + nuna, 1)
    draw_bin = s.take(L) < pb
    if nuna == 0:
        draw_bin = jnp.ones_like(draw_bin)
    if nbin == 0:
        draw_bin = jnp.zeros_like(draw_bin)
    cost = jnp.where(draw_bin, 2, 1).astype(jnp.int32)
    n_ops = jnp.minimum(jnp.asarray(nlength, jnp.int32), L)
    slot = jnp.arange(L, dtype=jnp.int32)
    take = (slot < n_ops) & (jnp.cumsum(cost) <= L - 1)
    n_binary = jnp.sum(take & draw_bin).astype(jnp.int32)
    n_unary = jnp.sum(take & ~draw_bin).astype(jnp.int32)
    return _random_postfix_from_counts(
        s.take(7 * L), n_binary, n_unary, ctx, dtype
    )


def randomize_tree(u, tree: TreeBatch, cur_maxsize, ctx: MutationContext):
    """Replace with a fresh random tree of size ~U(1, curmaxsize)
    (randomize_tree, :372-381). ``u``: [1 + 8L]."""
    s = USlice(u)
    target = u_randint(s.take1(), jnp.maximum(cur_maxsize, 1)) + 1
    new_tree = _gen_random_tree_fixed_size_u(
        s.take(gen_tree_nu(ctx)), target, ctx, tree.const.dtype
    )
    _assert_consumed(s, u, "randomize_tree")
    return new_tree, jnp.bool_(True)


def _select_tree(pred, a: TreeBatch, b: TreeBatch) -> TreeBatch:
    """Elementwise tree select. ``pred`` has batch shape; it is broadcast
    against each field's extra trailing dims (slot axis etc.)."""
    pred = jnp.asarray(pred)

    def sel(x, y):
        p = pred.reshape(pred.shape + (1,) * (x.ndim - pred.ndim))
        return jnp.where(p, x, y)

    return jax.tree.map(sel, a, b)
