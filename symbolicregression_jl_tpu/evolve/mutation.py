"""Device-side tree mutations on the postfix encoding.

Each function mirrors one tree-edit primitive of
/root/reference/src/MutationFunctions.jl, operating on a single unbatched
tree (fields ``[L]``) — callers vmap over candidates and speculative
attempts. Structural edits use the piece-concatenation gathers from
:mod:`.pieces`; value edits are masked writes. Every function returns
``(tree, ok)`` where ``ok=False`` marks a structurally impossible attempt
(e.g. result would exceed the slot budget), which the generation step
treats like a failed constraint check.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.encoding import (
    LEAF_CONST,
    LEAF_PARAM,
    LEAF_VAR,
    MAX_ARITY,
    TreeBatch,
    _tree_structure_single,
)
from .pieces import combine_sources, concat_pieces, splice_span
from .rng import masked_choice, randint_dyn

__all__ = [
    "MutationContext",
    "mutate_constant",
    "mutate_operator",
    "mutate_feature",
    "swap_operands",
    "rotate_tree",
    "add_node",
    "insert_node",
    "delete_node",
    "randomize_tree",
    "crossover_trees",
    "gen_random_tree",
    "gen_random_tree_fixed_size",
]


class MutationContext(NamedTuple):
    """Static + traced context shared by mutation kernels."""

    nops: Tuple[int, ...]  # static per-arity operator counts (1-based arity)
    nfeatures: int         # static
    max_nodes: int         # static (L)
    perturbation_factor: float
    probability_negate_constant: float
    n_params: int = 0      # static; >0 => parametric leaf sampling


def _slot_mask(tree: TreeBatch):
    return jnp.arange(tree.arity.shape[0]) < tree.length


def _structure(tree: TreeBatch, structure=None):
    """(child, size, depth); pass a precomputed tuple to avoid re-deriving
    it in every mutation branch of a speculative attempt."""
    if structure is not None:
        return structure
    return _tree_structure_single(tree.arity, tree.length)


def _span(size, k):
    """(start, length) of the subtree rooted at slot k."""
    return k - size[k] + 1, size[k]


# ---------------------------------------------------------------------------
# Value mutations
# ---------------------------------------------------------------------------


def _mutate_factor(key, temperature, ctx: MutationContext, dtype):
    """Constant perturbation factor (src/MutationFunctions.jl:150-162).

    Note: the reference negates when ``rand() > probability_negate_constant``
    (:158), i.e. ~99% of the time with default 0.00743 — contradicting both
    the parameter's docstring and its name. We implement the documented
    semantics (negate *with* probability `probability_negate_constant`).
    """
    k1, k2, k3 = jax.random.split(key, 3)
    bottom = 0.1
    max_change = ctx.perturbation_factor * temperature + 1.0 + bottom
    factor = jnp.asarray(max_change, dtype) ** jax.random.uniform(k1, dtype=dtype)
    bigger = jax.random.bernoulli(k2)
    factor = jnp.where(bigger, factor, 1.0 / factor)
    negate = jax.random.bernoulli(k3, ctx.probability_negate_constant)
    return jnp.where(negate, -factor, factor)


def mutate_constant(key, tree: TreeBatch, temperature, ctx: MutationContext):
    k1, k2 = jax.random.split(key)
    mask = _slot_mask(tree) & (tree.arity == 0) & (tree.op == LEAF_CONST)
    idx, has_any = masked_choice(k1, mask)
    factor = _mutate_factor(k2, temperature, ctx, tree.const.dtype)
    new_const = tree.const.at[idx].multiply(factor)
    const = jnp.where(has_any, new_const, tree.const)
    return TreeBatch(tree.arity, tree.op, tree.feat, const, tree.length), jnp.bool_(True)


def mutate_parameter_row(key, params, temperature, ctx: MutationContext):
    """Scale one whole parameter row (all classes) by a mutate factor
    (parametric mutate_constant branch,
    /root/reference/src/ParametricExpression.jl:173-191).

    ``params``: [n_params, n_classes]. No-op when there are no parameters.
    """
    if params.shape[-2] == 0:
        return params
    k1, k2 = jax.random.split(key)
    row = randint_dyn(k1, params.shape[-2])
    factor = _mutate_factor(k2, temperature, ctx, params.dtype)
    return params.at[row, :].multiply(factor)


def mutate_operator(key, tree: TreeBatch, ctx: MutationContext):
    k1, k2 = jax.random.split(key)
    mask = _slot_mask(tree) & (tree.arity > 0)
    idx, has_any = masked_choice(k1, mask)
    samples = [
        randint_dyn(jax.random.fold_in(k2, d), max(n, 1))
        for d, n in enumerate(ctx.nops, start=1)
    ]
    a = tree.arity[idx]
    new_op = jnp.int32(0)
    for d, s in enumerate(samples, start=1):
        new_op = jnp.where(a == d, s, new_op)
    op = jnp.where(has_any, tree.op.at[idx].set(new_op), tree.op)
    return TreeBatch(tree.arity, op, tree.feat, tree.const, tree.length), jnp.bool_(True)


def mutate_feature(key, tree: TreeBatch, ctx: MutationContext):
    k1, k2 = jax.random.split(key)
    mask = _slot_mask(tree) & (tree.arity == 0) & (tree.op == LEAF_VAR)
    idx, has_any = masked_choice(k1, mask)
    if ctx.nfeatures <= 1:
        return tree, jnp.bool_(True)
    # uniform among features != current (src/MutationFunctions.jl:181)
    delta = randint_dyn(k2, ctx.nfeatures - 1) + 1
    new_feat = (tree.feat[idx] + delta) % ctx.nfeatures
    feat = jnp.where(has_any, tree.feat.at[idx].set(new_feat), tree.feat)
    return TreeBatch(tree.arity, tree.op, feat, tree.const, tree.length), jnp.bool_(True)


# ---------------------------------------------------------------------------
# Structural mutations
# ---------------------------------------------------------------------------


def swap_operands(key, tree: TreeBatch, ctx: MutationContext, structure=None):
    """Swap the two child spans of a random binary node (:83-96)."""
    L = ctx.max_nodes
    child, size, _ = _structure(tree, structure)
    mask = _slot_mask(tree) & (tree.arity == 2)
    k_node, has_any = masked_choice(key, mask)
    c1 = child[k_node, 0]
    c2 = child[k_node, 1]
    s1, l1 = _span(size, c1)
    s2, l2 = _span(size, c2)
    sources = (tree.arity, tree.op, tree.feat, tree.const)
    starts = jnp.stack([jnp.int32(0), s2, s1, k_node, k_node + 1])
    lens = jnp.stack([s1, l2, l1, jnp.int32(1), tree.length - (k_node + 1)])
    new_tree, ok = concat_pieces(sources, starts, lens, L)
    return _select_tree(has_any, new_tree, tree), ok | ~has_any


def delete_node(key, tree: TreeBatch, ctx: MutationContext, structure=None):
    """Splice out a random operator node, keeping one child (:336-356)."""
    L = ctx.max_nodes
    k1, k2 = jax.random.split(key)
    child, size, _ = _structure(tree, structure)
    mask = _slot_mask(tree) & (tree.arity > 0)
    k_node, has_any = masked_choice(k1, mask)
    carry_i = randint_dyn(k2, jnp.maximum(tree.arity[k_node], 1))
    carry = child[k_node, jnp.clip(carry_i, 0, MAX_ARITY - 1)]
    node_start, node_len = _span(size, k_node)
    carry_start, carry_len = _span(size, carry)
    sources = (tree.arity, tree.op, tree.feat, tree.const)
    new_tree, ok = splice_span(
        tree, node_start, k_node, sources, carry_start, carry_len, L
    )
    return _select_tree(has_any, new_tree, tree), ok | ~has_any


def _sample_leaf(keys, ctx: MutationContext, dtype):
    """(op_code, feat, const) of one random leaf.

    Non-parametric: 50/50 constant ~ randn / variable ~ uniform feature
    (src/MutationFunctions.jl:321-333). Parametric (n_params > 0): uniform
    thirds constant / variable / parameter
    (make_random_leaf for ParametricNode,
    /root/reference/src/ParametricExpression.jl:113-137).
    """
    val = jax.random.normal(keys[1], dtype=dtype)
    f = randint_dyn(keys[2], ctx.nfeatures)
    if ctx.n_params > 0:
        choice = randint_dyn(keys[0], 3)
        p = randint_dyn(keys[3], ctx.n_params)
        code = jnp.where(
            choice == 0, LEAF_CONST, jnp.where(choice == 1, LEAF_VAR, LEAF_PARAM)
        )
        is_const = choice == 0
        feat = jnp.where(choice == 1, f, jnp.where(choice == 2, p, 0))
    else:
        is_const = jax.random.bernoulli(keys[0])
        code = jnp.where(is_const, LEAF_CONST, LEAF_VAR)
        feat = jnp.where(is_const, 0, f)
    return code, feat, jnp.where(is_const, val, jnp.zeros((), dtype))


def _make_leaf_scratch(key, n_slots, ctx: MutationContext, dtype):
    """Scratch arrays holding `n_slots` random leaves + one op slot.

    Layout: slots [0..MAX_ARITY-1] are random leaves (_sample_leaf); slot
    MAX_ARITY is reserved for a new operator node written by callers.
    """
    S = MAX_ARITY + 1
    keys = jax.random.split(key, MAX_ARITY * 4)
    arity = jnp.zeros((S,), jnp.int32)
    op = jnp.zeros((S,), jnp.int32)
    feat = jnp.zeros((S,), jnp.int32)
    const = jnp.zeros((S,), dtype)
    for j in range(MAX_ARITY):
        code, fj, cj = _sample_leaf(keys[4 * j:4 * j + 4], ctx, dtype)
        op = op.at[j].set(code)
        feat = feat.at[j].set(fj)
        const = const.at[j].set(cj)
    return arity, op, feat, const


def _sample_new_op(key, ctx: MutationContext, limit_arity=None):
    """Sample (arity, op_index) proportional to per-arity op counts
    (the csum draw at src/MutationFunctions.jl:209-221)."""
    k1, k2 = jax.random.split(key)
    D = len(ctx.nops)
    weights = jnp.asarray(ctx.nops, jnp.float32)
    if limit_arity is not None:
        weights = jnp.where(jnp.arange(1, D + 1) <= limit_arity, weights, 0.0)
    total = jnp.sum(weights)
    logits = jnp.where(weights > 0, jnp.log(jnp.maximum(weights, 1e-30)), -jnp.inf)
    a = jax.random.categorical(k1, logits).astype(jnp.int32) + 1
    samples = [
        randint_dyn(jax.random.fold_in(k2, d), max(n, 1))
        for d, n in enumerate(ctx.nops, start=1)
    ]
    o = jnp.int32(0)
    for d, s in enumerate(samples, start=1):
        o = jnp.where(a == d, s, o)
    return a, o, total > 0


def _expand_leaf_pieces(tree, scratch, k_node, node_start, node_len, new_arity,
                        carry_slot, ctx):
    """Pieces for replacing span [node_start, node_start+node_len) with a new
    operator node whose children are scratch leaves except `carry_slot`,
    which carries the original span. carry_slot=-1 means no carry (the
    original span is dropped — used by append where the target is a leaf).
    """
    L = ctx.max_nodes
    sources = combine_sources(
        tree,
        TreeBatch(scratch[0], scratch[1], scratch[2], scratch[3],
                  jnp.int32(MAX_ARITY + 1)),
    )
    starts = [jnp.int32(0)]
    lens = [node_start]
    for j in range(MAX_ARITY):
        in_use = j < new_arity
        is_carry = j == carry_slot
        starts.append(jnp.where(is_carry, node_start, L + j))
        lens.append(jnp.where(in_use, jnp.where(is_carry, node_len, 1), 0))
    # the new operator node (scratch slot MAX_ARITY)
    starts.append(jnp.int32(L + MAX_ARITY))
    lens.append(jnp.int32(1))
    # suffix
    starts.append(node_start + node_len)
    lens.append(tree.length - (node_start + node_len))
    return concat_pieces(sources, jnp.stack(starts), jnp.stack(lens), L)


def _write_op_slot(scratch, a, o):
    arity, op, feat, const = scratch
    arity = arity.at[MAX_ARITY].set(a)
    op = op.at[MAX_ARITY].set(o)
    return arity, op, feat, const


def add_node(key, tree: TreeBatch, ctx: MutationContext, structure=None):
    """append/prepend a random op, 50/50 (src/Mutate.jl:479-497)."""
    k0, k1 = jax.random.split(key)
    do_append = jax.random.bernoulli(k0)
    appended, ok_a = append_random_op(k1, tree, ctx, structure)
    prepended, ok_p = prepend_random_op(k1, tree, ctx)
    out = _select_tree(do_append, appended, prepended)
    return out, jnp.where(do_append, ok_a, ok_p)


def append_random_op(key, tree: TreeBatch, ctx: MutationContext, structure=None):
    """Replace a random leaf with op(random leaves) (:199-226)."""
    k1, k2, k3 = jax.random.split(key, 3)
    child, size, _ = _structure(tree, structure)
    mask = _slot_mask(tree) & (tree.arity == 0)
    k_leaf, has_any = masked_choice(k1, mask)
    a, o, any_op = _sample_new_op(k2, ctx)
    scratch = _make_leaf_scratch(k3, MAX_ARITY, ctx, tree.const.dtype)
    scratch = _write_op_slot(scratch, a, o)
    new_tree, ok = _expand_leaf_pieces(
        tree, scratch, k_leaf, k_leaf, jnp.int32(1), a, jnp.int32(-1), ctx
    )
    valid = has_any & any_op
    return _select_tree(valid, new_tree, tree), ok | ~valid


def insert_random_op(key, tree: TreeBatch, ctx: MutationContext, structure=None):
    """Wrap a random node inside a new op (:243-272)."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    child, size, _ = _structure(tree, structure)
    mask = _slot_mask(tree)
    k_node, has_any = masked_choice(k1, mask)
    a, o, any_op = _sample_new_op(k2, ctx)
    carry = randint_dyn(k4, jnp.maximum(a, 1))
    scratch = _make_leaf_scratch(k3, MAX_ARITY, ctx, tree.const.dtype)
    scratch = _write_op_slot(scratch, a, o)
    node_start, node_len = _span(size, k_node)
    new_tree, ok = _expand_leaf_pieces(
        tree, scratch, k_node, node_start, node_len, a, carry, ctx
    )
    valid = has_any & any_op
    return _select_tree(valid, new_tree, tree), ok | ~valid


def prepend_random_op(key, tree: TreeBatch, ctx: MutationContext):
    """New root with the old tree as a random child (:289-319)."""
    k1, k2, k3 = jax.random.split(key, 3)
    a, o, any_op = _sample_new_op(k1, ctx)
    carry = randint_dyn(k2, jnp.maximum(a, 1))
    scratch = _make_leaf_scratch(k3, MAX_ARITY, ctx, tree.const.dtype)
    scratch = _write_op_slot(scratch, a, o)
    new_tree, ok = _expand_leaf_pieces(
        tree, scratch, tree.length - 1, jnp.int32(0), tree.length, a, carry, ctx
    )
    return _select_tree(any_op, new_tree, tree), ok | ~any_op


def rotate_tree(key, tree: TreeBatch, ctx: MutationContext, structure=None):
    """AVL-style random rotation (randomly_rotate_tree!, :594-633).

    Chooses a rotation root R (an operator node with at least one operator
    child), a pivot P (operator child of R) and grandchild G (child of P);
    the rotated subtree is P with G's slot replaced by R' = R with P's slot
    replaced by G. Node count is preserved, so this is a permutation of
    spans — implemented as a 9-piece gather.
    """
    L = ctx.max_nodes
    k1, k2, k3 = jax.random.split(key, 3)
    child, size, _ = _structure(tree, structure)
    slot_ok = _slot_mask(tree)
    child_arity = tree.arity[jnp.clip(child, 0, L - 1)]  # [L, A]
    has_op_child = jnp.any(
        (child_arity > 0) & (jnp.arange(MAX_ARITY) < tree.arity[:, None]), axis=1
    )
    root_mask = slot_ok & (tree.arity > 0) & has_op_child
    r, has_root = masked_choice(k1, root_mask)

    pivot_mask = (jnp.arange(MAX_ARITY) < tree.arity[r]) & (child_arity[r] > 0)
    pi, _ = masked_choice(k2, pivot_mask)
    p = child[r, pi]
    gi = randint_dyn(k3, jnp.maximum(tree.arity[p], 1))
    g = child[p, jnp.clip(gi, 0, MAX_ARITY - 1)]

    def span_of(x):
        return x - size[x] + 1, size[x]

    g_start, g_len = span_of(g)
    # R' pieces: R's children in order with pivot slot -> G span; then R.
    rp_starts, rp_lens = [], []
    for i in range(MAX_ARITY):
        in_use = i < tree.arity[r]
        ci = child[r, i]
        ci_start, ci_len = span_of(ci)
        s = jnp.where(i == pi, g_start, ci_start)
        ln = jnp.where(i == pi, g_len, ci_len)
        rp_starts.append(jnp.where(in_use, s, 0))
        rp_lens.append(jnp.where(in_use, ln, 0))
    rp_starts.append(r)
    rp_lens.append(jnp.int32(1))

    # P' pieces: P's children in order, with G's slot -> the 3 R' pieces.
    starts, lens = [], []
    span_start, span_len = span_of(r)
    starts.append(jnp.int32(0))
    lens.append(span_start)
    for j in range(MAX_ARITY):
        in_use = j < tree.arity[p]
        cj = child[p, j]
        cj_start, cj_len = span_of(cj)
        is_g = j == gi
        # three sub-pieces: either the R' triple, or (child span, 0, 0)
        starts.append(jnp.where(is_g, rp_starts[0], cj_start))
        lens.append(jnp.where(in_use, jnp.where(is_g, rp_lens[0], cj_len), 0))
        starts.append(jnp.where(is_g, rp_starts[1], 0))
        lens.append(jnp.where(in_use & is_g, rp_lens[1], 0))
        starts.append(jnp.where(is_g, rp_starts[2], 0))
        lens.append(jnp.where(in_use & is_g, rp_lens[2], 0))
    starts.append(p)
    lens.append(jnp.int32(1))
    starts.append(span_start + span_len)
    lens.append(tree.length - (span_start + span_len))

    sources = (tree.arity, tree.op, tree.feat, tree.const)
    new_tree, ok = concat_pieces(sources, jnp.stack(starts), jnp.stack(lens), L)
    return _select_tree(has_root, new_tree, tree), ok | ~has_root


def crossover_trees(key, tree1: TreeBatch, tree2: TreeBatch, ctx: MutationContext,
                    structure1=None, structure2=None):
    """Random subtree exchange (crossover_trees, :488-518)."""
    L = ctx.max_nodes
    k1, k2 = jax.random.split(key)
    _, size1, _ = _structure(tree1, structure1)
    _, size2, _ = _structure(tree2, structure2)
    n1, _ = masked_choice(k1, _slot_mask(tree1))
    n2, _ = masked_choice(k2, _slot_mask(tree2))
    s1, l1 = _span(size1, n1)
    s2, l2 = _span(size2, n2)
    sources12 = combine_sources(tree1, tree2)
    child1, ok1 = splice_span(tree1, s1, n1, sources12, L + s2, l2, L)
    sources21 = combine_sources(tree2, tree1)
    child2, ok2 = splice_span(tree2, s2, n2, sources21, L + s1, l1, L)
    return child1, child2, ok1, ok2


# ---------------------------------------------------------------------------
# Random tree generation
# ---------------------------------------------------------------------------


def _make_single_leaf(key, ctx: MutationContext, dtype):
    keys = jax.random.split(key, 4)
    code, f0, c0 = _sample_leaf(keys, ctx, dtype)
    L = ctx.max_nodes
    t = TreeBatch(
        arity=jnp.zeros((L,), jnp.int32),
        op=jnp.zeros((L,), jnp.int32).at[0].set(code),
        feat=jnp.zeros((L,), jnp.int32).at[0].set(f0),
        const=jnp.zeros((L,), dtype).at[0].set(c0),
        length=jnp.int32(1),
    )
    return t


def _random_postfix_from_counts(key, n_binary, n_unary, ctx: MutationContext,
                                dtype):
    """Uniform random postfix tree with the given operator-arity counts.

    Loop-free construction (the reference grows trees by sequential leaf
    expansion, src/MutationFunctions.jl:441-471; a sequential loop is
    poison on TPU, so we sample the tree *shape* directly):

    1. lay out the arity multiset (``n_binary`` 2s, ``n_unary`` 1s,
       ``n_binary + 1`` 0s) and shuffle it with a masked argsort;
    2. rotate it into the unique valid postfix order via the cycle lemma
       (Dvoretzky–Motzkin: steps ``1 - arity`` sum to 1, so exactly one
       cyclic rotation keeps every prefix sum positive — start right
       after the last prefix-sum minimum);
    3. fill operator indices / leaf payloads with vectorized draws.

    This samples uniformly over tree shapes with the given op counts —
    a (documented) distributional delta from the reference's growth
    process, which biases toward unbalanced shapes.
    """
    L = ctx.max_nodes
    k_perm, k_ops1, k_ops2, k_leaf = jax.random.split(key, 4)
    slot = jnp.arange(L, dtype=jnp.int32)
    m = 2 * n_binary + n_unary + 1        # total nodes (traced scalar)
    live = slot < m

    vals = jnp.where(
        slot < n_binary, 2, jnp.where(slot < n_binary + n_unary, 1, 0)
    ).astype(jnp.int32)
    prio = jnp.where(live, jax.random.uniform(k_perm, (L,)), 2.0)
    perm = jnp.argsort(prio)
    arity = jnp.where(live, vals[perm], 0)

    # cycle-lemma rotation (dead slots get +inf so they never win the min)
    S = jnp.cumsum(1 - arity)
    S_masked = jnp.where(live, S, jnp.iinfo(jnp.int32).max)
    minS = jnp.min(S_masked)
    t = jnp.max(jnp.where(S_masked == minS, slot, -1))   # last argmin
    p = jnp.where(t + 1 >= m, 0, t + 1)
    src = jnp.where(live, (p + slot) % jnp.maximum(m, 1), slot)
    arity = jnp.where(live, arity[src], 0)

    # operator indices per arity
    nuna = ctx.nops[0] if len(ctx.nops) >= 1 else 0
    nbin = ctx.nops[1] if len(ctx.nops) >= 2 else 0
    op_u = randint_dyn(k_ops1, max(nuna, 1), (L,))
    op_b = randint_dyn(k_ops2, max(nbin, 1), (L,))

    # leaf payloads (vectorized _sample_leaf semantics)
    ks = jax.random.split(k_leaf, 4)
    const_vals = jax.random.normal(ks[1], (L,), dtype=dtype)
    feat_vals = randint_dyn(ks[2], ctx.nfeatures, (L,))
    if ctx.n_params > 0:
        choice = randint_dyn(ks[0], 3, (L,))
        p_vals = randint_dyn(ks[3], ctx.n_params, (L,))
        leaf_code = jnp.where(
            choice == 0, LEAF_CONST, jnp.where(choice == 1, LEAF_VAR, LEAF_PARAM)
        )
        leaf_feat = jnp.where(choice == 1, feat_vals,
                              jnp.where(choice == 2, p_vals, 0))
        is_const = choice == 0
    else:
        is_const = jax.random.bernoulli(ks[0], shape=(L,))
        leaf_code = jnp.where(is_const, LEAF_CONST, LEAF_VAR)
        leaf_feat = jnp.where(is_const, 0, feat_vals)

    op = jnp.where(
        arity == 2, op_b, jnp.where(arity == 1, op_u, leaf_code)
    ).astype(jnp.int32)
    feat = jnp.where((arity == 0) & live, leaf_feat, 0).astype(jnp.int32)
    const = jnp.where(
        (arity == 0) & live & is_const, const_vals, jnp.zeros((), dtype)
    )
    return TreeBatch(arity=arity, op=op, feat=feat, const=const,
                     length=m.astype(jnp.int32))


def _sample_arity_counts(key, budget, ctx: MutationContext):
    """(n_binary, n_unary) from iid arity draws filling ``budget`` size
    increments (binary costs 2, unary 1), matching the reference growth
    loop's weighted arity sampling in aggregate."""
    L = ctx.max_nodes
    nuna = ctx.nops[0] if len(ctx.nops) >= 1 else 0
    nbin = ctx.nops[1] if len(ctx.nops) >= 2 else 0
    if nbin == 0 and nuna == 0:
        z = jnp.zeros((), jnp.int32)
        return z, z
    pb = nbin / max(nbin + nuna, 1)
    draw_bin = jax.random.bernoulli(key, pb, (L,))
    if nuna == 0:
        draw_bin = jnp.ones_like(draw_bin)
    if nbin == 0:
        draw_bin = jnp.zeros_like(draw_bin)
    cost = jnp.where(draw_bin, 2, 1).astype(jnp.int32)
    csum = jnp.cumsum(cost)
    take = csum <= budget
    n_binary = jnp.sum(take & draw_bin).astype(jnp.int32)
    n_unary = jnp.sum(take & ~draw_bin).astype(jnp.int32)
    if nuna > 0:
        # fill a leftover single size unit with one unary op
        total = jnp.max(jnp.where(take, csum, 0))
        n_unary = n_unary + jnp.where(budget - total >= 1, 1, 0)
    return n_binary, n_unary


def gen_random_tree_fixed_size(key, node_count, ctx: MutationContext, dtype,
                               n_steps=None):
    """Random tree of ~``node_count`` nodes
    (gen_random_tree_fixed_size, src/MutationFunctions.jl:441-471)."""
    del n_steps  # legacy knob of the sequential-growth implementation
    k1, k2 = jax.random.split(key)
    budget = jnp.clip(node_count, 1, ctx.max_nodes) - 1
    n_binary, n_unary = _sample_arity_counts(k1, budget, ctx)
    return _random_postfix_from_counts(k2, n_binary, n_unary, ctx, dtype)


def gen_random_tree(key, nlength, ctx: MutationContext, dtype):
    """Random tree from ``nlength`` weighted op draws (gen_random_tree,
    :384-398 appends `nlength` ops; sizes land in [nlength+1, 2*nlength+1])."""
    L = ctx.max_nodes
    k1, k2, k3 = jax.random.split(key, 3)
    nuna = ctx.nops[0] if len(ctx.nops) >= 1 else 0
    nbin = ctx.nops[1] if len(ctx.nops) >= 2 else 0
    if nbin == 0 and nuna == 0:
        return _make_single_leaf(k1, ctx, dtype)
    pb = nbin / max(nbin + nuna, 1)
    draw_bin = jax.random.bernoulli(k1, pb, (L,))
    if nuna == 0:
        draw_bin = jnp.ones_like(draw_bin)
    if nbin == 0:
        draw_bin = jnp.zeros_like(draw_bin)
    cost = jnp.where(draw_bin, 2, 1).astype(jnp.int32)
    n_ops = jnp.minimum(jnp.asarray(nlength, jnp.int32), L)
    slot = jnp.arange(L, dtype=jnp.int32)
    take = (slot < n_ops) & (jnp.cumsum(cost) <= L - 1)
    n_binary = jnp.sum(take & draw_bin).astype(jnp.int32)
    n_unary = jnp.sum(take & ~draw_bin).astype(jnp.int32)
    return _random_postfix_from_counts(k3, n_binary, n_unary, ctx, dtype)


def randomize_tree(key, tree: TreeBatch, cur_maxsize, ctx: MutationContext):
    """Replace with a fresh random tree of size ~U(1, curmaxsize)
    (randomize_tree, :372-381)."""
    k1, k2 = jax.random.split(key)
    target = randint_dyn(k1, jnp.maximum(cur_maxsize, 1)) + 1
    new_tree = gen_random_tree_fixed_size(k2, target, ctx, tree.const.dtype)
    return new_tree, jnp.bool_(True)


def _select_tree(pred, a: TreeBatch, b: TreeBatch) -> TreeBatch:
    """Elementwise tree select. ``pred`` has batch shape; it is broadcast
    against each field's extra trailing dims (slot axis etc.)."""
    pred = jnp.asarray(pred)

    def sel(x, y):
        p = pred.reshape(pred.shape + (1,) * (x.ndim - pred.ndim))
        return jnp.where(p, x, y)

    return jax.tree.map(sel, a, b)
