"""``python -m symbolicregression_jl_tpu.telemetry`` entry point."""

import sys

from .report import main

if __name__ == "__main__":
    sys.exit(main())
