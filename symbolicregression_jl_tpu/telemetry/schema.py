"""graftscope JSONL event schema (version 2) + hand-rolled validator.

Every line the `Telemetry` hub emits is one JSON object with at least::

    {"schema": "graftscope.v2", "event": <type>, "t": <unix seconds>}

Event types and their required fields are listed in :data:`EVENT_SPECS`.
No external jsonschema dependency: the validator is a small table-driven
checker (CI validates every emitted line with it, and the report CLI
refuses files that don't validate — see docs/OBSERVABILITY.md for the
full field semantics).

v2 adds the optional graftledger ``trace`` field — a
``{"trace_id", "span_id", "parent_id"}`` causal-context object
(ledger/context.py) the hub stamps onto every event it emits. The
validator type-checks ``trace`` when present but does not require it:
pre-v2 streams (schema ``graftscope.v1``) still validate unchanged, and
synthetic v2 events without a trace (bench fixtures) stay valid too.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Tuple

__all__ = ["SCHEMA_VERSION", "SCHEMA_VERSIONS", "EVENT_SPECS",
           "validate_event", "validate_lines", "load_events",
           "load_events_tolerant"]

SCHEMA_VERSION = "graftscope.v2"

# every schema version the validator accepts, oldest first; v1 events
# (no trace field) remain valid forever — the bump is purely additive
SCHEMA_VERSIONS = ("graftscope.v1", "graftscope.v2")

_NUM = (int, float)

# event type -> {field: type or tuple of types}. Fields may hold None
# where noted ("nullable" set). Unknown extra fields are allowed (the
# schema is forward-extensible; v2 consumers must ignore them too).
EVENT_SPECS: Dict[str, Dict[str, Any]] = {
    "run_start": {
        "run_id": str,
        "backend": str,
        "n_devices": int,
        "nout": int,
        "niterations": int,
        "telemetry_interval": int,
        "options": dict,
        "engines": list,
    },
    "iteration": {
        "iteration": int,
        "num_evals": _NUM,
        "evals_per_sec": _NUM,
        "elapsed_s": _NUM,
        "device_s": _NUM,
        "host_s": _NUM,
        "host_fraction": _NUM,
        "recompiles": dict,
        "transfer_guard_hits": int,
        "outputs": list,
    },
    "run_end": {
        "stop_reason": str,
        "iterations": int,
        "num_evals": _NUM,
        "elapsed_s": _NUM,
        "recompiles_total": dict,
    },
    # graftshield fault/recovery audit records (docs/ROBUSTNESS.md):
    # kind is one of preempt_signal / emergency_checkpoint / retry /
    # degrade / quarantine / watchdog_timeout / checkpoint_corrupt /
    # injected (fault harness); detail carries kind-specific fields
    # (attempt counts, island lists, error text).
    "fault": {
        "kind": str,
        "iteration": int,
        "detail": dict,
    },
    # graftserve request-lifecycle audit records (docs/SERVING.md):
    # kind is one of accept / reject / start / done / cancel / failed /
    # interrupted / replay / cache_hit / cache_miss / injected /
    # shutdown;
    # request_id ties the event to one journaled request (report's
    # per-request view groups on it, falling back to run_id for plain
    # search events); detail carries kind-specific fields (shape bucket,
    # queue depth, retry-after, result fingerprint).
    "serve": {
        "kind": str,
        "request_id": str,
        "detail": dict,
    },
    # graftmesh shard-runtime records (docs/SCALING.md): the periodic
    # cross-shard dedup-key exchange and per-shard balance view.
    # detail carries rows / shard_unique / global_unique / local_dup /
    # cross_shard_dup / per_shard_unique / shard_imbalance /
    # exchanged_bytes / exchange_time_s / sharded_dedup.
    "mesh": {
        "iteration": int,
        "shards": int,
        "detail": dict,
    },
    # graftpulse anomaly-detector findings (docs/OBSERVABILITY.md): a
    # rolling EWMA/z-score excursion on one watched per-iteration metric
    # (evals_per_sec / host_fraction / recompiles / invalid_fraction).
    # detail carries value / mean / zscore / threshold and, when the
    # excursion armed a profiler capture, armed_capture=true.
    "anomaly": {
        "metric": str,
        "iteration": int,
        "detail": dict,
    },
    # graftpulse diagnostics-layer audit records: kind is one of
    # capture_armed / capture_start / capture_stop / capture_failed /
    # bundle_dump / profiler_unusable; detail carries kind-specific
    # fields (reason, trace_dir, trace files/bytes, bundle path).
    "pulse": {
        "kind": str,
        "iteration": int,
        "detail": dict,
    },
    # graftgauge capacity-observability records (docs/OBSERVABILITY.md,
    # "Capacity & memory"): kind is one of memory (per-iteration live
    # bytes + allocator stats) / footprint (one compiled executable's
    # memory/cost analysis) / watermark (end-of-run peaks) /
    # dispatch_latency (end-of-run histogram summary); detail carries
    # kind-specific fields. Additive within graftscope.v2 — the schema
    # allows unknown event fields but not unknown event TYPES, so the
    # entry here is what lets v2 consumers see gauge streams; v1
    # streams (which never contain gauge events) validate unchanged.
    "gauge": {
        "kind": str,
        "iteration": int,
        "detail": dict,
    },
}

# required keys inside each element of iteration.outputs; nullable
# fields are expressed as (type, type(None)) tuples
_OUTPUT_FIELDS: Dict[str, Any] = {
    "output": int,
    "min_loss": (_NUM, type(None)),
    "pareto_volume": _NUM,
    "counters": (dict, type(None)),
    "loss_hist": (list, type(None)),
    "complexity_hist": (list, type(None)),
}

# required keys inside the optional top-level `trace` field (v2,
# ledger/context.py): parent_id is nullable (None at the tree root)
_TRACE_FIELDS: Dict[str, Any] = {
    "trace_id": str,
    "span_id": str,
    "parent_id": (str, type(None)),
}

# required keys inside iteration.outputs[*].counters when present
_COUNTER_FIELDS: Dict[str, Any] = {
    "proposed": dict,
    "accepted": dict,
    "reject_reasons": dict,
    "candidates": int,
    "invalid": int,
    "eval_rows": int,
    "eval_launches": int,
    "dedup": dict,
}

# graftstage staged-eval counters: emitted by every post-graftstage
# stream, but optional in the schema so pre-graftstage artifacts still
# validate. Type-checked when present.
_OPTIONAL_COUNTER_FIELDS: Dict[str, Any] = {
    "screen_rows": int,
    "screen_launches": int,
    "rescore_rows": int,
    "rescore_launches": int,
}


def _type_ok(value, spec) -> bool:
    if isinstance(spec, tuple):
        flat: Tuple[type, ...] = ()
        for s in spec:
            flat += s if isinstance(s, tuple) else (s,)
        spec = flat
    ok = isinstance(value, spec)
    # bool is an int subclass; reject it where a number is expected
    if ok and isinstance(value, bool) and not (
        spec is bool or (isinstance(spec, tuple) and bool in spec)
    ):
        return False
    return ok


def _check_fields(obj: dict, fields: Dict[str, Any], where: str,
                  errors: List[str]) -> None:
    for name, spec in fields.items():
        if name not in obj:
            errors.append(f"{where}: missing field {name!r}")
        elif not _type_ok(obj[name], spec):
            errors.append(
                f"{where}: field {name!r} has type "
                f"{type(obj[name]).__name__}, expected {spec}"
            )


def validate_event(obj: Any) -> List[str]:
    """Validate one decoded JSONL event; return violation strings
    (empty list = valid)."""
    errors: List[str] = []
    if not isinstance(obj, dict):
        return [f"event is {type(obj).__name__}, expected object"]
    if obj.get("schema") not in SCHEMA_VERSIONS:
        errors.append(
            f"schema is {obj.get('schema')!r}, expected one of "
            f"{SCHEMA_VERSIONS!r}"
        )
    trace = obj.get("trace")
    if trace is not None:
        if not isinstance(trace, dict):
            errors.append(
                f"trace is {type(trace).__name__}, expected object")
        else:
            _check_fields(trace, _TRACE_FIELDS, "trace", errors)
    ev = obj.get("event")
    if ev not in EVENT_SPECS:
        errors.append(
            f"event type {ev!r} not one of {sorted(EVENT_SPECS)}"
        )
        return errors
    if not _type_ok(obj.get("t"), _NUM):
        errors.append(f"{ev}: field 't' must be a unix timestamp")
    _check_fields(obj, EVENT_SPECS[ev], ev, errors)
    if ev == "iteration" and isinstance(obj.get("outputs"), list):
        for i, out in enumerate(obj["outputs"]):
            where = f"iteration.outputs[{i}]"
            if not isinstance(out, dict):
                errors.append(f"{where}: not an object")
                continue
            _check_fields(out, _OUTPUT_FIELDS, where, errors)
            counters = out.get("counters")
            if isinstance(counters, dict):
                _check_fields(
                    counters, _COUNTER_FIELDS, where + ".counters", errors
                )
                for name, spec in _OPTIONAL_COUNTER_FIELDS.items():
                    if name in counters and not _type_ok(
                            counters[name], spec):
                        errors.append(
                            f"{where}.counters: field {name!r} has type "
                            f"{type(counters[name]).__name__}, "
                            f"expected {spec}"
                        )
    if ev == "iteration" and isinstance(obj.get("recompiles"), dict):
        for k in ("traces", "backend_compiles"):
            if not isinstance(obj["recompiles"].get(k), int):
                errors.append(f"iteration.recompiles.{k}: missing/not int")
    return errors


def validate_lines(lines: Iterable[str]) -> List[str]:
    """Validate raw JSONL lines; returns one violation string per
    problem, prefixed with the 1-based line number."""
    errors: List[str] = []
    n = 0
    for lineno, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        n += 1
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            errors.append(f"line {lineno}: invalid JSON ({e})")
            continue
        errors.extend(f"line {lineno}: {m}" for m in validate_event(obj))
    if n == 0:
        errors.append("no events found (empty file)")
    return errors


def load_events(path: str) -> List[dict]:
    """Load + validate a JSONL run file; raises ValueError with the full
    violation list on any schema problem."""
    with open(path) as f:
        lines = f.readlines()
    errors = validate_lines(lines)
    if errors:
        raise ValueError(
            f"{path} failed {SCHEMA_VERSION} validation:\n  "
            + "\n  ".join(errors[:20])
            + ("" if len(errors) <= 20 else f"\n  ... +{len(errors) - 20} more")
        )
    return [json.loads(l) for l in lines if l.strip()]


def load_events_tolerant(path: str) -> Tuple[List[dict], List[dict]]:
    """Load a possibly-live or crashed stream, skip-and-count bad lines.

    A writer that crashed (or is still appending) leaves a partial last
    line; ``load_events`` would refuse the whole file over it. This
    loader mirrors serve/journal.py replay: every undecodable or
    schema-invalid line is SKIPPED and returned as a corrupt note
    ``{"line": n, "reason": ..., "torn_tail": bool}`` — torn_tail is
    True only for the final line (the expected crash/live artifact);
    anything earlier is mid-file corruption, reported but not fatal.
    """
    with open(path) as f:
        raw = f.readlines()
    numbered = [(i, l.strip()) for i, l in enumerate(raw, start=1)
                if l.strip()]
    events: List[dict] = []
    notes: List[dict] = []
    last_lineno = numbered[-1][0] if numbered else 0
    for lineno, line in numbered:
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            notes.append({"line": lineno, "reason": f"invalid JSON ({e})",
                          "torn_tail": lineno == last_lineno})
            continue
        errs = validate_event(obj)
        if errs:
            notes.append({"line": lineno, "reason": errs[0],
                          "torn_tail": lineno == last_lineno})
            continue
        events.append(obj)
    return events, notes
