"""graftscope — device-resident search telemetry, span tracing, run reports.

The observability layer of the TPU port (ROADMAP: "what is the search
doing and what is pacing it", answerable on every run):

- :mod:`.counters` — device-side metric accumulators threaded through the
  evolve scan carry (mutation proposals/accepts per kind, invalid-eval
  fraction, dedup hit-rate, eval launches, population histograms). They
  ride the engine state, so the host fetches them with the existing
  per-iteration state pull: 0 extra dispatches, 0 extra transfers,
  0 retraces in the hot loop.
- :mod:`.hub` — the host-side ``Telemetry`` hub: merges device counters
  with ``ResourceMonitor`` timings and ``jax.monitoring`` compile
  events, emits schema-versioned JSONL (:mod:`.schema`), and dispatches
  registered sinks (``SRLogger``, ``Recorder``, ``ProgressBar``).
- :mod:`.spans` — ``jax.profiler`` span annotations so a perfetto /
  xplane capture lines up with search iterations and host phases.
- :mod:`.report` — the run-report CLI::

      python -m symbolicregression_jl_tpu.telemetry report run.jsonl
      python -m symbolicregression_jl_tpu.telemetry validate run.jsonl
      python -m symbolicregression_jl_tpu.telemetry timeline root --out t.json

Enable with ``Options(telemetry=True)``; see docs/OBSERVABILITY.md.
"""

from .counters import (
    CycleTelemetry,
    IterationTelemetry,
    empty_cycle_telemetry,
    empty_iteration_telemetry,
)
from .hub import IterationContext, Telemetry
from .schema import (
    SCHEMA_VERSION,
    SCHEMA_VERSIONS,
    validate_event,
    validate_lines,
)

__all__ = [
    "CycleTelemetry",
    "IterationTelemetry",
    "IterationContext",
    "Telemetry",
    "SCHEMA_VERSION",
    "SCHEMA_VERSIONS",
    "empty_cycle_telemetry",
    "empty_iteration_telemetry",
    "validate_event",
    "validate_lines",
]
