"""``telemetry tail`` — follow a live graftscope stream.

A graftscope JSONL file is append-only and crash-torn at worst, which
makes it a perfectly good live surface: this follower re-reads only the
bytes appended since its last poll, holds any partial final line in a
buffer until the writer finishes it (a live stream ALWAYS has a torn
tail mid-write — that is not corruption), and folds each complete event
into a rolling single-screen summary::

    python -m symbolicregression_jl_tpu.telemetry tail run.jsonl
    python -m symbolicregression_jl_tpu.telemetry tail run.jsonl --once

``--interval`` sets the refresh period (default 1s); ``--once`` renders
the current state once and exits (scripts, tests). The screen shows the
run header, the latest iteration's throughput/loss/host-fraction, and
the fault / anomaly / pulse / serve counters — the "is it healthy right
now" view that ``telemetry report`` gives post-mortem.

Pure host-side text processing; no jax import.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

__all__ = ["TailState", "TailFollower", "main"]


class TailState:
    """Rolling summary of the events seen so far."""

    def __init__(self) -> None:
        self.run: Optional[Dict[str, Any]] = None
        self.last_iter: Optional[Dict[str, Any]] = None
        self.iterations = 0
        self.faults: Dict[str, int] = {}
        self.anomalies: Dict[str, int] = {}
        self.pulse: Dict[str, int] = {}
        self.serve: Dict[str, int] = {}
        self.gauge: Dict[str, int] = {}
        # latest graftgauge memory sample (live/peak bytes), for the
        # "is it about to OOM" line
        self.last_memory: Optional[Dict[str, Any]] = None
        self.mesh_exchanges = 0
        self.end: Optional[Dict[str, Any]] = None
        self.events = 0
        self.skipped = 0

    def update(self, e: Dict[str, Any]) -> None:
        self.events += 1
        ev = e.get("event")
        if ev == "run_start":
            self.run = e
        elif ev == "iteration":
            self.last_iter = e
            self.iterations = max(self.iterations, int(e.get("iteration", 0)))
        elif ev == "fault":
            k = e.get("kind", "?")
            self.faults[k] = self.faults.get(k, 0) + 1
        elif ev == "anomaly":
            k = e.get("metric", "?")
            self.anomalies[k] = self.anomalies.get(k, 0) + 1
        elif ev == "pulse":
            k = e.get("kind", "?")
            self.pulse[k] = self.pulse.get(k, 0) + 1
        elif ev == "serve":
            k = e.get("kind", "?")
            self.serve[k] = self.serve.get(k, 0) + 1
        elif ev == "gauge":
            k = e.get("kind", "?")
            self.gauge[k] = self.gauge.get(k, 0) + 1
            if k in ("memory", "watermark"):
                self.last_memory = e.get("detail") or {}
        elif ev == "mesh":
            self.mesh_exchanges += 1
        elif ev == "run_end":
            self.end = e

    def render(self) -> str:
        """The single-screen summary (bounded line count)."""
        lines: List[str] = []
        r = self.run or {}
        niter = r.get("niterations")
        lines.append(
            f"run {r.get('run_id', '?')}  [{r.get('backend', '?')} x "
            f"{r.get('n_devices', '?')} device(s)]  "
            f"{self.events} events"
            + (f", {self.skipped} torn/skipped" if self.skipped else "")
        )
        it = self.last_iter
        if it is not None:
            frac = (f"{self.iterations}/{niter}" if niter
                    else str(self.iterations))
            lines.append(
                f"iteration {frac}  |  evals/s "
                f"{it.get('evals_per_sec', 0):,.3g}  |  best loss "
                f"{it.get('best_loss', float('nan')):.6g}  |  host "
                f"{100.0 * it.get('host_fraction', 0.0):.1f}%  |  evals "
                f"{it.get('num_evals', 0):,.3g}"
            )
            rc = it.get("recompiles") or {}
            if rc.get("traces"):
                lines.append(f"  recompiles this event: {rc['traces']}")
        else:
            lines.append("iteration -  (no iteration events yet)")
        mem = self.last_memory
        if mem:
            live = mem.get("live_bytes")
            peak = mem.get("peak_live_bytes")
            in_use = mem.get("bytes_in_use")
            bits = []
            if live is not None:
                bits.append(f"live {live:,} B")
            if peak is not None:
                bits.append(f"peak {peak:,} B")
            if in_use is not None:
                bits.append(f"allocator {in_use:,} B")
            if bits:
                lines.append("memory: " + "  |  ".join(bits))
        for label, counts in (("faults", self.faults),
                              ("anomalies", self.anomalies),
                              ("pulse", self.pulse),
                              ("gauge", self.gauge),
                              ("serve", self.serve)):
            if counts:
                body = ", ".join(
                    f"{k}={v}" for k, v in sorted(counts.items()))
                lines.append(f"{label}: {body}")
        if self.mesh_exchanges:
            lines.append(f"mesh: {self.mesh_exchanges} exchange(s)")
        if self.end is not None:
            lines.append(
                f"run END: {self.end.get('stop_reason')} after "
                f"{self.end.get('iterations')} iterations, "
                f"{self.end.get('num_evals', 0):,.3g} evals in "
                f"{self.end.get('elapsed_s', 0):,.1f}s"
            )
        else:
            lines.append("run live...")
        return "\n".join(lines)


class TailFollower:
    """Incremental reader: new bytes only, partial tail buffered."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.state = TailState()
        self._pos = 0
        self._buf = ""
        self._ino: Optional[tuple] = None  # (st_dev, st_ino) of last poll

    def poll(self) -> int:
        """Fold newly-appended complete lines into the state; returns
        how many events arrived. Missing file = 0 (writer not up yet).
        A file that SHRANK (truncation) or whose identity changed
        (rotation: rename-and-recreate swaps the inode, possibly with a
        LARGER new file) is a new run over the same path — restart the
        summary from byte 0 rather than silently mixing two runs or
        stalling on a stale offset."""
        try:
            st = os.stat(self.path)
        except OSError:
            return 0
        ident = (st.st_dev, st.st_ino)
        if st.st_size < self._pos or (
                self._ino is not None and ident != self._ino):
            self.state = TailState()
            self._pos = 0
            self._buf = ""
        self._ino = ident
        with open(self.path) as f:
            f.seek(self._pos)
            chunk = f.read()
            self._pos = f.tell()
        self._buf += chunk
        # everything before the last newline is complete; the remainder
        # stays buffered (the torn tail of a mid-write writer)
        complete, sep, rest = self._buf.rpartition("\n")
        if not sep:
            return 0
        self._buf = rest
        n = 0
        for line in complete.split("\n"):
            if not line.strip():
                continue
            try:
                e = json.loads(line)
            except json.JSONDecodeError:
                self.state.skipped += 1
                continue
            if isinstance(e, dict):
                self.state.update(e)
                n += 1
            else:
                self.state.skipped += 1
        return n


_CLEAR = "\x1b[2J\x1b[H"


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    once = "--once" in argv
    interval = 1.0
    if "--interval" in argv:
        i = argv.index("--interval")
        try:
            interval = float(argv[i + 1])
            del argv[i:i + 2]
        except (IndexError, ValueError):
            print("--interval needs a number of seconds", file=sys.stderr)
            return 2
    paths = [a for a in argv if not a.startswith("-")]
    if len(paths) != 1:
        print("usage: telemetry tail <run.jsonl> [--interval S] [--once]",
              file=sys.stderr)
        return 2
    follower = TailFollower(paths[0])
    try:
        while True:
            follower.poll()
            screen = follower.state.render()
            if once:
                print(screen)
                return 0
            sys.stdout.write(_CLEAR + screen + "\n")
            sys.stdout.flush()
            if follower.state.end is not None:
                return 0
            time.sleep(max(interval, 0.05))
    except KeyboardInterrupt:
        print()
        return 0
