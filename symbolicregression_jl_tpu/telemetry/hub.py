"""The host-side Telemetry hub.

One object owns everything the search loop used to wire ad-hoc in
api/search.py: the ``SRLogger`` callback, the genealogy ``Recorder``,
the ``ProgressBar``, and (new) the graftscope JSONL stream. Per
iteration the hub:

1. fetches the device counters (``state.telem``) with one explicit
   ``jax.device_get`` — the only host<->device traffic telemetry adds,
   riding the per-iteration sync the loop already performs;
2. merges them with ``ResourceMonitor``-style timings and the
   ``jax.monitoring`` compile events observed since the last iteration;
3. emits a schema-versioned JSONL ``iteration`` event every
   ``options.telemetry_interval`` iterations (counters summed across
   the interval);
4. dispatches the registered sinks under an ``sr:host:sinks`` span.

Sinks implement ``on_iteration(ctx)`` / ``on_end(summary)``; adapters
for the three existing consumers live here so api/search.py registers
them in one line each.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.options import MUTATION_KINDS
from ..ledger.context import TraceContext, mint_run_trace
from .schema import SCHEMA_VERSION
from .spans import host_span, set_profiler_warning_hook

__all__ = [
    "IterationContext",
    "Telemetry",
    "LoggerSink",
    "RecorderSink",
    "ProgressSink",
]

_KIND_NAMES = tuple(MUTATION_KINDS) + ("crossover",)
_REASON_NAMES = ("none", "constraint", "invalid", "annealing")


@dataclasses.dataclass
class IterationContext:
    """Everything one iteration hands to the sinks."""

    iteration: int
    states: Sequence[Any]          # per-output SearchDeviceState
    hofs: Sequence[Any]            # per-output HallOfFame
    options: Any
    num_evals: float
    elapsed: float
    best_loss: float
    evals_per_sec: float
    device_s: float
    host_s: float
    host_fraction: float
    events: Sequence[Any]          # per-output CycleEvents or None
    counters: Sequence[Optional[Dict[str, Any]]] = ()


class _CompileEventCounter:
    """Counts jax.monitoring compile/transfer events for the hub (same
    event names graftlint's compile_count_guard pins in tests)."""

    def __init__(self) -> None:
        self.traces = 0
        self.backend_compiles = 0
        self.transfer_guard_hits = 0
        # graftledger: the compile-seconds the same events carry — the
        # cost ledger diffs these the way the anomaly detector diffs the
        # counts (wall-clock, so ledger accounts keep them out of the
        # deterministic view)
        self.trace_secs = 0.0
        self.backend_compile_secs = 0.0
        self._active = False

    def _on_duration(self, name: str, secs: float, **kw) -> None:
        if not self._active:
            return
        if name.endswith("jaxpr_trace_duration"):
            self.traces += 1
            self.trace_secs += float(secs or 0.0)
        elif name.endswith("backend_compile_duration") or name.endswith(
            "backend_compile_time"
        ):
            self.backend_compiles += 1
            self.backend_compile_secs += float(secs or 0.0)
        elif "transfer_guard" in name:  # emitted by some jax versions only
            self.transfer_guard_hits += 1

    def start(self) -> None:
        from jax._src import monitoring

        self._active = True
        monitoring.register_event_duration_secs_listener(self._on_duration)

    def stop(self) -> None:
        self._active = False
        try:
            from jax._src import monitoring

            unreg = getattr(
                monitoring,
                "_unregister_event_duration_listener_by_callback", None)
            if unreg is not None:
                unreg(self._on_duration)
        except Exception:  # pragma: no cover - best-effort cleanup
            pass

    def snapshot(self) -> Dict[str, int]:
        return {
            "traces": self.traces,
            "backend_compiles": self.backend_compiles,
            "transfer_guard_hits": self.transfer_guard_hits,
        }

    def seconds_snapshot(self) -> Dict[str, float]:
        """Cumulative compile wall-seconds (kept out of :meth:`snapshot`
        so count consumers — recompiles_total, the anomaly detector —
        never see float fields)."""
        return {
            "trace_s": self.trace_secs,
            "backend_compile_s": self.backend_compile_secs,
        }


def _counters_to_dict(telem) -> Optional[Dict[str, Any]]:
    """IterationTelemetry (device pytree) -> plain JSON-ready dict."""
    if telem is None:
        return None
    import jax

    t = jax.device_get(telem)  # one explicit pull for the whole pytree
    proposed = np.asarray(t.cycle.proposed).tolist()
    accepted = np.asarray(t.cycle.accepted).tolist()
    reasons = np.asarray(t.cycle.reject_reasons).tolist()
    rows = int(t.finalize_rows)
    unique = int(t.finalize_unique)
    return {
        "proposed": dict(zip(_KIND_NAMES, proposed)),
        "accepted": dict(zip(_KIND_NAMES, accepted)),
        "reject_reasons": dict(zip(_REASON_NAMES[1:], reasons[1:])),
        "candidates": int(t.cycle.candidates),
        "invalid": int(t.cycle.invalid),
        "eval_rows": int(t.cycle.eval_rows),
        "eval_launches": int(t.cycle.eval_launches),
        "screen_rows": int(t.cycle.screen_rows),
        "screen_launches": int(t.cycle.screen_launches),
        "rescore_rows": int(t.cycle.rescore_rows),
        "rescore_launches": int(t.cycle.rescore_launches),
        "dedup": {
            "rows": rows,
            "unique": unique,
            "hits": max(rows - unique, 0),
        },
        "loss_hist": np.asarray(t.loss_hist).tolist(),
        "complexity_hist": np.asarray(t.cx_hist).tolist(),
    }


def _merge_counts(acc: Dict[str, Any], new: Dict[str, Any]) -> Dict[str, Any]:
    """Sum two counter dicts (interval accumulation)."""
    out = dict(acc)
    for key in ("proposed", "accepted", "reject_reasons", "dedup"):
        out[key] = {
            k: acc[key].get(k, 0) + new[key].get(k, 0)
            for k in set(acc[key]) | set(new[key])
        }
    for key in ("candidates", "invalid", "eval_rows", "eval_launches",
                "screen_rows", "screen_launches", "rescore_rows",
                "rescore_launches"):
        # .get: pre-graftstage snapshots carry no screen/rescore keys
        out[key] = acc.get(key, 0) + new.get(key, 0)
    for key in ("loss_hist", "complexity_hist"):
        out[key] = [a + b for a, b in zip(acc[key], new[key])]
    return out


class Telemetry:
    """The search-loop telemetry hub (see module docstring).

    Always constructed by ``equation_search`` (sink dispatch replaces
    the old ad-hoc wiring); the JSONL stream only exists when
    ``options.telemetry`` is set and this is process 0.
    """

    def __init__(
        self,
        options,
        *,
        run_id: str,
        out_dir: Optional[str],
        niterations: int,
        nout: int,
        engine_info: Optional[List[Dict[str, Any]]] = None,
        trace: Optional[TraceContext] = None,
    ) -> None:
        import jax

        self.options = options
        self.run_id = run_id
        # graftledger causal context: served searches thread the child
        # span of their request's journaled root through RuntimeOptions;
        # plain searches fall back to a deterministic run_id mint — so
        # EVERY event this hub emits carries a trace (graftscope.v2).
        self.trace = trace if trace is not None else mint_run_trace(run_id)
        self.interval = max(int(getattr(options, "telemetry_interval", 1)), 1)
        self._sinks: List[Any] = []
        self._compiles = _CompileEventCounter()
        self._last_compiles = self._compiles.snapshot()
        self._acc: List[Optional[Dict[str, Any]]] = [None] * nout
        self._acc_device_s = 0.0
        self._acc_host_s = 0.0
        self._pending = False
        self._last_ctx: Optional[IterationContext] = None
        self._iterations_seen = 0
        # graftshield fault audit: per-kind counts, always tracked (the
        # run_end event reports them even at telemetry_interval > 1).
        self.fault_counts: Dict[str, int] = {}
        # graftpulse: per-metric anomaly counts (same always-tracked
        # contract as fault_counts) and event watchers — callbacks that
        # observe every out-of-band event (fault/mesh/anomaly/pulse)
        # even when the JSONL stream is off. The flight recorder
        # (pulse/recorder.py) registers here so a fault can trigger its
        # bundle dump before a watchdog abort kills the process.
        self.anomaly_counts: Dict[str, int] = {}
        self._watchers: List[Callable[[Dict[str, Any]], None]] = []

        self.path: Optional[str] = None
        enabled = bool(getattr(options, "telemetry", False))
        if enabled and jax.process_index() == 0:
            fname = getattr(options, "telemetry_file", "telemetry.jsonl")
            self.path = (
                fname if os.path.isabs(fname)
                else os.path.join(out_dir or ".", fname)
            )
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            # truncate any stale file from a previous run with this id
            open(self.path, "w").close()
        self._compiles.start()
        # spans.py satellite contract: when a profiler annotation is
        # requested but jax.profiler is unusable, the first failure per
        # process surfaces as a pulse event instead of a silent no-op
        # ("the trace is empty" becomes diagnosable).
        set_profiler_warning_hook(
            lambda msg: self.pulse("profiler_unusable", error=msg))
        if self.path is not None:
            self._emit({
                "event": "run_start",
                "run_id": run_id,
                "backend": jax.default_backend(),
                "n_devices": len(jax.devices()),
                "nout": nout,
                "niterations": int(niterations),
                "telemetry_interval": self.interval,
                "options": {
                    "maxsize": options.maxsize,
                    "populations": options.populations,
                    "population_size": options.population_size,
                    "ncycles_per_iteration": options.ncycles_per_iteration,
                    "batching": options.batching,
                    "batch_size": options.batch_size,
                    "telemetry_file": getattr(
                        options, "telemetry_file", "telemetry.jsonl"),
                },
                "engines": list(engine_info or []),
            })

    # ------------------------------------------------------------------
    def add_sink(self, sink) -> "Telemetry":
        self._sinks.append(sink)
        return self

    def add_watcher(self, fn: Callable[[Dict[str, Any]], None]
                    ) -> "Telemetry":
        """Register an out-of-band event observer: called with every
        fault/mesh/anomaly/pulse/gauge event dict, stream on or off. Watcher
        exceptions are swallowed — observation must never break the
        path it observes (the same contract sinks have)."""
        self._watchers.append(fn)
        return self

    def _notify(self, event: Dict[str, Any]) -> None:
        for fn in self._watchers:
            try:
                fn(event)
            except Exception:  # observers must never break the search
                pass

    # ------------------------------------------------------------------
    def fault(self, kind: str, *, iteration: int = 0,
              **detail) -> Dict[str, Any]:
        """Record a graftshield fault/recovery event (schema ``fault``).

        Always cheap and never raises into the recovery path it audits:
        counted in-process even when the JSONL stream is off, emitted to
        the stream when it is on."""
        event = {
            "event": "fault",
            "kind": str(kind),
            "iteration": int(iteration),
            "detail": {
                k: v for k, v in detail.items() if v is not None
            },
        }
        self.fault_counts[kind] = self.fault_counts.get(kind, 0) + 1
        if self.path is not None:
            try:
                self._emit(event)
            except OSError:  # auditing must not break the recovery
                pass
        self._notify(event)
        return event

    def mesh(self, *, iteration: int, shards: int,
             **detail) -> Dict[str, Any]:
        """Record a graftmesh shard-runtime event (schema ``mesh``):
        the periodic cross-shard dedup-key exchange results and shard
        balance. Observability only — cheap, never raises into the
        search loop, emitted only when the JSONL stream is on."""
        event = {
            "event": "mesh",
            "iteration": int(iteration),
            "shards": int(shards),
            "detail": {k: v for k, v in detail.items() if v is not None},
        }
        if self.path is not None:
            try:
                self._emit(event)
            except OSError:  # observability must not break the search
                pass
        self._notify(event)
        return event

    def anomaly(self, metric: str, *, iteration: int = 0,
                **detail) -> Dict[str, Any]:
        """Record a graftpulse anomaly-detector finding (schema
        ``anomaly``): a rolling-statistics excursion on one watched
        per-iteration metric. Same discipline as ``fault``: counted
        in-process always, streamed when the JSONL stream is on, never
        raises into the loop it observes."""
        event = {
            "event": "anomaly",
            "metric": str(metric),
            "iteration": int(iteration),
            "detail": {k: v for k, v in detail.items() if v is not None},
        }
        self.anomaly_counts[metric] = self.anomaly_counts.get(metric, 0) + 1
        if self.path is not None:
            try:
                self._emit(event)
            except OSError:
                pass
        self._notify(event)
        return event

    def pulse(self, kind: str, *, iteration: int = 0,
              **detail) -> Dict[str, Any]:
        """Record a graftpulse diagnostics audit event (schema
        ``pulse``): capture windows armed/started/stopped, bundle
        dumps, profiler-unusable warnings."""
        event = {
            "event": "pulse",
            "kind": str(kind),
            "iteration": int(iteration),
            "detail": {k: v for k, v in detail.items() if v is not None},
        }
        if self.path is not None:
            try:
                self._emit(event)
            except OSError:
                pass
        self._notify(event)
        return event

    def gauge(self, kind: str, *, iteration: int = 0,
              **detail) -> Dict[str, Any]:
        """Record a graftgauge capacity-observability event (schema
        ``gauge``): per-iteration memory samples, compiled-executable
        footprints, end-of-run watermarks and dispatch-latency
        summaries. Same discipline as ``pulse``: streamed when the
        JSONL stream is on, watchers notified either way, never raises
        into the loop it observes."""
        event = {
            "event": "gauge",
            "kind": str(kind),
            "iteration": int(iteration),
            "detail": {k: v for k, v in detail.items() if v is not None},
        }
        if self.path is not None:
            try:
                self._emit(event)
            except OSError:
                pass
        self._notify(event)
        return event

    def compile_snapshot(self) -> Dict[str, int]:
        """Cumulative jax.monitoring compile/transfer counts seen so far
        (the anomaly detector diffs consecutive snapshots for its
        per-iteration recompile signal)."""
        return self._compiles.snapshot()

    def compile_seconds_snapshot(self) -> Dict[str, float]:
        """Cumulative compile wall-seconds (the cost ledger diffs these
        for its per-iteration compile_s attribution)."""
        return self._compiles.seconds_snapshot()

    def _emit(self, obj: Dict[str, Any]) -> None:
        # run_id on EVERY event (not just run_start) so concatenated or
        # multi-tenant streams stay attributable: `telemetry report`
        # groups records by run_id/request_id (docs/SERVING.md).
        obj = {
            "schema": SCHEMA_VERSION, "t": time.time(),
            "run_id": self.run_id, "trace": self.trace.to_dict(), **obj,
        }
        with open(self.path, "a") as f:
            f.write(json.dumps(obj) + "\n")

    # ------------------------------------------------------------------
    def iteration(self, ctx: IterationContext) -> Optional[Dict[str, Any]]:
        """Record one iteration: accumulate counters, maybe emit the
        JSONL event, dispatch sinks. Returns the emitted event (None
        when this iteration fell inside an interval)."""
        self._iterations_seen = ctx.iteration
        event = None
        if self.path is not None:
            # The counter fetch is the one host<->device transfer
            # telemetry adds; only the JSONL stream consumes it, so
            # processes without one (telemetry off, or non-zero ranks
            # under multi-host) skip the pull — and the accumulator,
            # which only _emit_iteration ever resets.
            counters = [
                _counters_to_dict(getattr(s, "telem", None))
                for s in ctx.states
            ]
            ctx.counters = counters
            for j, c in enumerate(counters):
                if c is None:
                    continue
                self._acc[j] = c if self._acc[j] is None else _merge_counts(
                    self._acc[j], c)
            self._acc_device_s += ctx.device_s
            self._acc_host_s += ctx.host_s
            self._pending = True
            self._last_ctx = ctx
            if ctx.iteration % self.interval == 0:
                event = self._emit_iteration(ctx)

        with host_span("sinks"):
            for sink in self._sinks:
                sink.on_iteration(ctx)
        return event

    def _emit_iteration(self, ctx: IterationContext) -> Dict[str, Any]:
        snap = self._compiles.snapshot()
        delta = {k: snap[k] - self._last_compiles[k] for k in snap}
        self._last_compiles = snap
        outputs = []
        for j, hof in enumerate(ctx.hofs):
            frontier = hof.pareto_frontier()
            losses = [e.loss for e in frontier]
            complexities = [e.complexity for e in frontier]
            from ..utils.logging import pareto_volume

            acc = self._acc[j]
            out: Dict[str, Any] = {
                "output": j + 1,
                "min_loss": float(min(losses)) if losses else None,
                "pareto_volume": pareto_volume(
                    losses, complexities, ctx.options.maxsize,
                    use_linear_scaling=(ctx.options.loss_scale == "linear"),
                ),
                "counters": None,
                "loss_hist": None,
                "complexity_hist": None,
            }
            if acc is not None:
                acc = dict(acc)
                out["loss_hist"] = acc.pop("loss_hist")
                out["complexity_hist"] = acc.pop("complexity_hist")
                out["counters"] = acc
            outputs.append(out)
        event = {
            "event": "iteration",
            "iteration": ctx.iteration,
            "num_evals": float(ctx.num_evals),
            "evals_per_sec": float(ctx.evals_per_sec),
            "elapsed_s": float(ctx.elapsed),
            "device_s": float(self._acc_device_s),
            "host_s": float(self._acc_host_s),
            "host_fraction": float(ctx.host_fraction),
            "recompiles": {
                "traces": delta["traces"],
                "backend_compiles": delta["backend_compiles"],
            },
            "transfer_guard_hits": delta["transfer_guard_hits"],
            "outputs": outputs,
        }
        self._emit(event)
        self._acc = [None] * len(self._acc)
        self._acc_device_s = 0.0
        self._acc_host_s = 0.0
        self._pending = False
        return event

    # ------------------------------------------------------------------
    def close(self) -> None:
        """Release process-global resources (the jax.monitoring compile
        listener). Idempotent; ``finish`` calls it, and the search loop
        calls it again in a ``finally`` so an interrupted or failing
        search cannot leak a listener per fit."""
        self._compiles.stop()

    def finish(self, *, stop_reason: str, num_evals: float,
               elapsed: float) -> None:
        """Flush any partial interval, emit run_end, close sinks."""
        if (self.path is not None and self._pending
                and self._last_ctx is not None):
            self._emit_iteration(self._last_ctx)
        self.close()
        if self.path is not None:
            self._emit({
                "event": "run_end",
                "stop_reason": stop_reason,
                "iterations": int(self._iterations_seen),
                "num_evals": float(num_evals),
                "elapsed_s": float(elapsed),
                "recompiles_total": {
                    k: v for k, v in self._compiles.snapshot().items()
                    if k != "transfer_guard_hits"
                },
                # extra (schema-optional) fields: per-kind graftshield
                # fault counts and per-metric graftpulse anomaly counts
                # for the whole run
                "faults_total": dict(self.fault_counts),
                "anomalies_total": dict(self.anomaly_counts),
            })
        summary = {
            "stop_reason": stop_reason,
            "num_evals": float(num_evals),
            "elapsed_s": float(elapsed),
        }
        for sink in self._sinks:
            on_end = getattr(sink, "on_end", None)
            if on_end is not None:
                on_end(summary)


# ---------------------------------------------------------------------------
# Sink adapters for the pre-existing consumers
# ---------------------------------------------------------------------------


class LoggerSink:
    """SRLogger-compatible sink (any object with ``log_iteration``)."""

    def __init__(self, logger, every: int = 1) -> None:
        import inspect

        self.logger = logger
        self.every = max(int(every), 1)
        # host_fraction is new in the hub contract; user loggers written
        # against the original signature keep working.
        try:
            params = inspect.signature(logger.log_iteration).parameters
            self._pass_host_fraction = "host_fraction" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            )
        except (TypeError, ValueError):  # builtins / C callables
            self._pass_host_fraction = False

    def on_iteration(self, ctx: IterationContext) -> None:
        if ctx.iteration % self.every != 0:
            return
        kw = {}
        if self._pass_host_fraction:
            kw["host_fraction"] = ctx.host_fraction
        self.logger.log_iteration(
            iteration=ctx.iteration, hofs=ctx.hofs, states=ctx.states,
            options=ctx.options, num_evals=ctx.num_evals,
            elapsed=ctx.elapsed, **kw,
        )


class RecorderSink:
    """Genealogy Recorder sink; owns the end-of-run write."""

    def __init__(self, recorder, variable_names: Sequence[Sequence[str]],
                 path: str) -> None:
        self.recorder = recorder
        self.variable_names = list(variable_names)
        self.path = path

    def on_iteration(self, ctx: IterationContext) -> None:
        events = ctx.events or [None] * len(ctx.states)
        for j, state in enumerate(ctx.states):
            self.recorder.record_iteration(
                ctx.iteration, j, state, ctx.hofs[j],
                float(state.num_evals),
                variable_names=self.variable_names[j],
                events=events[j],
            )

    def on_end(self, summary: Dict[str, Any]) -> None:
        self.recorder.record_final("stop_reason", summary["stop_reason"])
        self.recorder.record_final("num_evals", summary["num_evals"])
        self.recorder.write(self.path)


class ProgressSink:
    """Terminal progress-bar sink."""

    def __init__(self, bar) -> None:
        self.bar = bar

    def on_iteration(self, ctx: IterationContext) -> None:
        self.bar.update(
            ctx.iteration, best_loss=ctx.best_loss,
            evals_per_sec=ctx.evals_per_sec,
            host_fraction=ctx.host_fraction,
        )

    def on_end(self, summary: Dict[str, Any]) -> None:
        self.bar.close()
