"""graftscope run-report CLI.

Summarizes a graftscope.v1 JSONL run file (see :mod:`.schema` and
docs/OBSERVABILITY.md)::

    python -m symbolicregression_jl_tpu.telemetry report run.jsonl
    python -m symbolicregression_jl_tpu.telemetry report run.jsonl --json
    python -m symbolicregression_jl_tpu.telemetry validate run.jsonl

``report`` refuses files that fail schema validation (run ``validate``
for the full violation list). ``--json`` emits the machine-readable
summary dict instead of the human-readable text. Pure host-side JSON
processing — no accelerator or jax session is touched.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional

from .schema import (
    SCHEMA_VERSION,
    load_events,
    load_events_tolerant,
    validate_lines,
)

__all__ = ["summarize", "summarize_requests", "metrics_view",
           "format_report", "main"]


def _mean(xs: List[float]) -> Optional[float]:
    return sum(xs) / len(xs) if xs else None


def _rate(num: int, den: int) -> Optional[float]:
    return num / den if den else None


_SERVE_LIFECYCLE = ("accept", "start", "interrupted", "done", "cancel",
                    "reject", "failed")


def _request_key(e: dict) -> Optional[str]:
    """The human-readable grouping key of one event for the per-request
    view: an explicit request_id (serve events; fault events emitted by
    the serve layer carry it in detail), else the emitting search's
    run_id."""
    rid = e.get("request_id")
    if not rid and isinstance(e.get("detail"), dict):
        rid = e["detail"].get("request_id")
    return (rid or e.get("run_id")) or None


def _trace_id(e: dict) -> Optional[str]:
    trace = e.get("trace")
    if isinstance(trace, dict):
        tid = trace.get("trace_id")
        return tid if isinstance(tid, str) else None
    return None


def summarize_requests(events: List[dict]) -> Dict[str, Any]:
    """Group graftscope records into the per-request view of a
    multi-tenant (graftserve) or concatenated stream.

    Events group by graftledger ``trace_id`` when present (v2), falling
    back to request_id/run_id — so a mixed v1+v2 directory (old runs
    next to new ones) still groups every event, and two streams of one
    request join on the causal id even when their human ids differ.
    Returned groups stay keyed by the human-readable id (the first
    request_id/run_id seen for each trace); events with neither id are
    ignored."""
    # pass 1: canonical human key per trace_id (first seen wins)
    canon: Dict[str, str] = {}
    for e in events:
        tid = _trace_id(e)
        if tid is None or tid in canon:
            continue
        canon[tid] = _request_key(e) or tid
    groups: Dict[str, Dict[str, Any]] = {}
    for e in events:
        tid = _trace_id(e)
        key = canon[tid] if tid is not None else _request_key(e)
        if key is None:
            continue
        g = groups.setdefault(key, {
            "events": 0, "iterations": 0, "num_evals": None,
            "faults": {}, "serve": {}, "state": None,
            "first_t": None, "last_t": None, "stop_reason": None,
            "trace_id": None, "padding": None,
        })
        if tid is not None and g["trace_id"] is None:
            g["trace_id"] = tid
        g["events"] += 1
        t = e.get("t")
        if isinstance(t, (int, float)):
            g["first_t"] = t if g["first_t"] is None else min(g["first_t"], t)
            g["last_t"] = t if g["last_t"] is None else max(g["last_t"], t)
        kind = e.get("kind")
        if e["event"] == "iteration":
            g["iterations"] = max(g["iterations"], int(e["iteration"]))
            g["num_evals"] = e.get("num_evals")
        elif e["event"] == "run_end":
            g["stop_reason"] = e.get("stop_reason")
            g["iterations"] = max(g["iterations"],
                                  int(e.get("iterations", 0)))
        elif e["event"] == "fault":
            g["faults"][kind] = g["faults"].get(kind, 0) + 1
        elif e["event"] == "serve":
            g["serve"][kind] = g["serve"].get(kind, 0) + 1
            if kind in _SERVE_LIFECYCLE:
                g["state"] = kind
            if kind == "accept":
                # graftpack padded-bucket provenance from the journaled
                # accept record: replay/audit reads it back here rather
                # than re-deriving the padding from shapes
                det = e.get("detail") or {}
                if det.get("bucket_rows"):
                    g["padding"] = {
                        "bucket_rows": det.get("bucket_rows"),
                        "pad_rows": det.get("pad_rows"),
                        "sample_rows": det.get("sample_rows"),
                    }
    for g in groups.values():
        if g["first_t"] is not None and g["last_t"] is not None:
            g["span_s"] = g["last_t"] - g["first_t"]
    return groups


def _summarize_serve(serve: List[dict]) -> Dict[str, Any]:
    """Fleet-level aggregates of graftserve events: lifecycle counts,
    executable-cache hit rate (overall and per shape bucket), admission
    rejections."""
    kinds: Dict[str, int] = {}
    for e in serve:
        kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
    hits = kinds.get("cache_hit", 0)
    misses = kinds.get("cache_miss", 0)
    by_bucket: Dict[str, Dict[str, int]] = {}
    for e in serve:
        if e["kind"] not in ("cache_hit", "cache_miss"):
            continue
        b = str(e.get("detail", {}).get("bucket"))
        d = by_bucket.setdefault(b, {"hits": 0, "misses": 0})
        d["hits" if e["kind"] == "cache_hit" else "misses"] += 1
    for d in by_bucket.values():
        d["hit_rate"] = _rate(d["hits"], d["hits"] + d["misses"])
    out = {
        "events": len(serve),
        "by_kind": kinds,
        "accepted": kinds.get("accept", 0),
        "rejected": kinds.get("reject", 0),
        "cache": {
            "hits": hits,
            "misses": misses,
            "hit_rate": _rate(hits, hits + misses),
            "by_bucket": by_bucket,
        },
    }
    # graftpack aggregates: launches, multi-tenant launches, mean
    # occupancy (from pack_done), and how much padding admission added
    padded = pad_rows_total = tenants = multi = 0
    occ: List[float] = []
    for e in serve:
        det = e.get("detail") or {}
        if e["kind"] == "accept" and det.get("pad_rows"):
            padded += 1
            pad_rows_total += int(det["pad_rows"])
        elif e["kind"] == "pack_launch":
            t = det.get("tenants") or []
            tenants += len(t)
            if len(t) > 1:
                multi += 1
        elif e["kind"] == "pack_join":
            tenants += 1
        elif e["kind"] == "pack_done":
            if isinstance(det.get("occupancy"), (int, float)):
                occ.append(float(det["occupancy"]))
    if kinds.get("pack_launch") or padded:
        out["packing"] = {
            "launches": kinds.get("pack_launch", 0),
            "multi_tenant_launches": multi,
            "tenants": tenants,
            "padded_accepts": padded,
            "pad_rows_total": pad_rows_total,
            "mean_occupancy": (round(sum(occ) / len(occ), 4)
                               if occ else None),
        }
    return out


def summarize(events: List[dict]) -> Dict[str, Any]:
    """Machine-readable summary of a validated event list."""
    run_start = next((e for e in events if e["event"] == "run_start"), None)
    run_end = next((e for e in events if e["event"] == "run_end"), None)
    iters = [e for e in events if e["event"] == "iteration"]
    faults = [e for e in events if e["event"] == "fault"]
    serve = [e for e in events if e["event"] == "serve"]

    summary: Dict[str, Any] = {"schema": SCHEMA_VERSION}
    if run_start is not None:
        summary["run"] = {
            k: run_start.get(k)
            for k in ("run_id", "backend", "n_devices", "nout",
                      "niterations", "telemetry_interval")
        }
        summary["run"]["options"] = run_start.get("options", {})
        summary["run"]["engines"] = run_start.get("engines", [])

    evals_curve = [[e["iteration"], e["evals_per_sec"]] for e in iters]
    host_fracs = [e["host_fraction"] for e in iters]
    recompile_traces = sum(e["recompiles"]["traces"] for e in iters)
    recompile_backend = sum(e["recompiles"]["backend_compiles"] for e in iters)
    warm = [e for e in iters[1:] if e["recompiles"]["traces"] == 0]
    summary["iterations"] = {
        "count": len(iters),
        "evals_per_sec": {
            "curve": evals_curve,
            "final": evals_curve[-1][1] if evals_curve else None,
            "peak": max((v for _, v in evals_curve), default=None),
        },
        "host_fraction": {
            "mean": _mean(host_fracs),
            "max": max(host_fracs, default=None),
            "final": host_fracs[-1] if host_fracs else None,
        },
        "recompiles": {
            "traces": recompile_traces,
            "backend_compiles": recompile_backend,
            "warm_iterations": len(warm),
            # the one place the warm rule (post-first event, zero
            # traces) is computed; metrics_view gates on these ids
            "warm_iteration_ids": [e["iteration"] for e in warm],
        },
        "transfer_guard_hits": sum(
            e.get("transfer_guard_hits", 0) for e in iters
        ),
    }

    # Per-output aggregates across every iteration event that carried
    # counters (intervals already sum within each event).
    nout = max((len(e["outputs"]) for e in iters), default=0)
    outputs = []
    for j in range(nout):
        outs = [e["outputs"][j] for e in iters if len(e["outputs"]) > j]
        counters = [o["counters"] for o in outs if o.get("counters")]
        agg: Dict[str, Any] = {
            "output": j + 1,
            "pareto_volume_curve": [
                [e["iteration"], e["outputs"][j]["pareto_volume"]]
                for e in iters if len(e["outputs"]) > j
            ],
            "final_min_loss": outs[-1]["min_loss"] if outs else None,
        }
        if counters:
            kinds = sorted(
                {k for c in counters for k in c["proposed"]}
            )
            proposed = {
                k: sum(c["proposed"].get(k, 0) for c in counters)
                for k in kinds
            }
            accepted = {
                k: sum(c["accepted"].get(k, 0) for c in counters)
                for k in kinds
            }
            agg["proposed"] = proposed
            agg["accepted"] = accepted
            agg["acceptance_rate"] = {
                k: _rate(accepted[k], proposed[k])
                for k in kinds if proposed[k]
            }
            agg["reject_reasons"] = {
                r: sum(c["reject_reasons"].get(r, 0) for c in counters)
                for r in sorted(
                    {r for c in counters for r in c["reject_reasons"]}
                )
            }
            cands = sum(c["candidates"] for c in counters)
            agg["candidates"] = cands
            agg["invalid_fraction"] = _rate(
                sum(c["invalid"] for c in counters), cands
            )
            agg["eval_rows"] = sum(c["eval_rows"] for c in counters)
            agg["eval_launches"] = sum(c["eval_launches"] for c in counters)
            # graftstage staged-eval counters (.get: pre-graftstage
            # streams don't carry them)
            screen = sum(c.get("screen_rows", 0) for c in counters)
            if screen:
                agg["screen_rows"] = screen
                agg["rescore_rows"] = sum(
                    c.get("rescore_rows", 0) for c in counters)
                agg["screen_launches"] = sum(
                    c.get("screen_launches", 0) for c in counters)
                agg["rescore_launches"] = sum(
                    c.get("rescore_launches", 0) for c in counters)
                agg["observed_rescore_fraction"] = _rate(
                    agg["rescore_rows"], screen)
                # the raw invalid_fraction includes the structural
                # unrescored-NaN floor (docs/PRECISION.md); this is the
                # storm-relevant fraction among rescored candidates
                unrescored = screen - agg["rescore_rows"]
                agg["rescored_invalid_fraction"] = _rate(
                    max(0, sum(c["invalid"] for c in counters) - unrescored),
                    max(1, cands - unrescored),
                )
            dedup_rows = sum(c["dedup"]["rows"] for c in counters)
            agg["dedup_hit_rate"] = _rate(
                sum(c["dedup"]["hits"] for c in counters), dedup_rows
            )
        outputs.append(agg)
    summary["outputs"] = outputs

    # graftshield fault/recovery audit (docs/ROBUSTNESS.md): per-kind
    # counts plus the raw timeline (kind, iteration) for small runs.
    if faults:
        by_kind: Dict[str, int] = {}
        for e in faults:
            by_kind[e["kind"]] = by_kind.get(e["kind"], 0) + 1
        summary["faults"] = {
            "count": len(faults),
            "by_kind": by_kind,
            "timeline": [[e["iteration"], e["kind"]] for e in faults[:50]],
        }

    # graftmesh exchange view (docs/SCALING.md): the periodic
    # cross-shard dedup-key exchanges, aggregated. Duplication split =
    # what per-shard dedup exploits vs what only a cross-shard scheme
    # could reach.
    mesh = [e for e in events if e["event"] == "mesh"]
    if mesh:
        last = mesh[-1].get("detail", {})
        rows = sum(e.get("detail", {}).get("rows", 0) for e in mesh)
        local_dup = sum(
            e.get("detail", {}).get("local_dup", 0) for e in mesh)
        cross_dup = sum(
            e.get("detail", {}).get("cross_shard_dup", 0) for e in mesh)
        summary["mesh"] = {
            "exchanges": len(mesh),
            "shards": mesh[-1].get("shards"),
            "local_dup_fraction": _rate(local_dup, rows),
            "cross_shard_dup_fraction": _rate(cross_dup, rows),
            "last_shard_imbalance": last.get("shard_imbalance"),
            "last_shard_eval_imbalance": last.get("shard_eval_imbalance"),
            "exchanged_bytes_total": sum(
                e.get("detail", {}).get("exchanged_bytes", 0)
                for e in mesh),
            "exchange_time_s_total": sum(
                e.get("detail", {}).get("exchange_time_s", 0.0)
                for e in mesh),
            "sharded_dedup": last.get("sharded_dedup"),
        }

    # graftpulse anomaly view (docs/OBSERVABILITY.md): detector
    # excursions, per metric, with the small-run timeline — and the
    # pulse audit trail (capture windows, bundle dumps).
    anomalies = [e for e in events if e["event"] == "anomaly"]
    if anomalies:
        by_metric: Dict[str, int] = {}
        for e in anomalies:
            by_metric[e["metric"]] = by_metric.get(e["metric"], 0) + 1
        summary["anomalies"] = {
            "count": len(anomalies),
            "by_metric": by_metric,
            "timeline": [
                [e["iteration"], e["metric"]] for e in anomalies[:50]
            ],
        }
    pulse = [e for e in events if e["event"] == "pulse"]
    if pulse:
        pk: Dict[str, int] = {}
        for e in pulse:
            pk[e["kind"]] = pk.get(e["kind"], 0) + 1
        summary["pulse"] = {
            "count": len(pulse),
            "by_kind": pk,
            "captures": pk.get("capture_stop", 0),
            "bundles": pk.get("bundle_dump", 0),
        }

    # graftgauge capacity view (docs/OBSERVABILITY.md, "Capacity &
    # memory"): peak live bytes across memory/watermark events, the
    # end-of-run dispatch-latency histogram summary, and the footprint
    # events' count + largest program.
    gauges = [e for e in events if e["event"] == "gauge"]
    if gauges:
        gk: Dict[str, int] = {}
        peak_live = None
        latency = None
        footprint_max = None
        for e in gauges:
            gk[e["kind"]] = gk.get(e["kind"], 0) + 1
            d = e.get("detail", {})
            if e["kind"] in ("memory", "watermark"):
                p = d.get("peak_live_bytes", d.get("live_bytes"))
                if p is not None and (peak_live is None or p > peak_live):
                    peak_live = p
            elif e["kind"] == "dispatch_latency":
                latency = {
                    k: d.get(k)
                    for k in ("count", "sum_s", "max_s", "p50_s", "p99_s")
                }
            elif e["kind"] == "footprint":
                total = (d.get("summary") or {}).get("total_bytes")
                if total and (footprint_max is None
                              or total > footprint_max):
                    footprint_max = total
        summary["gauge"] = {
            "count": len(gauges),
            "by_kind": gk,
            "peak_live_bytes": peak_live,
            "dispatch_latency": latency,
            "footprints": gk.get("footprint", 0),
            "footprint_max_bytes": footprint_max,
        }

    # graftserve per-request view (docs/SERVING.md): the serve event
    # stream always gets one; a plain search stream gets one only when
    # it actually interleaves multiple run_ids.
    request_groups = summarize_requests(events)
    if serve:
        summary["serve"] = _summarize_serve(serve)
        summary["requests"] = request_groups
    elif len(request_groups) > 1:
        summary["requests"] = request_groups

    if run_end is not None:
        summary["end"] = {
            k: run_end.get(k)
            for k in ("stop_reason", "iterations", "num_evals", "elapsed_s",
                      "recompiles_total")
        }
        if run_end.get("faults_total"):
            summary.setdefault("faults", {})["totals_at_end"] = (
                run_end["faults_total"]
            )
        if run_end.get("anomalies_total"):
            summary.setdefault("anomalies", {})["totals_at_end"] = (
                run_end["anomalies_total"]
            )
    return summary


def metrics_view(summary: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten a :func:`summarize` dict to the scalar metrics the
    graftbench regression gate consumes (docs/BENCHMARKING.md): one
    number per gated dimension, chosen for per-run stability.

    ``evals_per_sec`` prefers the mean over WARM iterations (no traces,
    excluding the first event, whose window absorbs compile) and falls
    back to the peak — on CPU the first-iteration rate is dominated by
    trace time and would gate on compiler noise, not throughput.
    """
    it = summary["iterations"]
    curve = it["evals_per_sec"]["curve"]
    # warm ids come from summarize (zero traces, first event excluded
    # — its window absorbs startup compile even when nothing retraced):
    # a mid-run retrace's rate must not leak into the gated mean
    warm_iters = set(it["recompiles"].get("warm_iteration_ids", []))
    warm_vals = [v for i, v in curve if i in warm_iters]
    eps = (_mean(warm_vals) if warm_vals
           else it["evals_per_sec"]["peak"])
    outputs = summary.get("outputs") or []
    best_loss = None
    pareto_volume = None
    for out in outputs:
        fl = out.get("final_min_loss")
        if fl is not None and (best_loss is None or fl > best_loss):
            best_loss = fl  # worst output gates (multi-output runs)
        pv = out.get("pareto_volume_curve") or []
        if pv:
            v = pv[-1][1]
            pareto_volume = v if pareto_volume is None else min(
                pareto_volume, v)
    end = summary.get("end") or {}
    return {
        "evals_per_sec": eps,
        "evals_per_sec_final": it["evals_per_sec"]["final"],
        "best_loss": best_loss,
        "pareto_volume": pareto_volume,
        "host_fraction": it["host_fraction"]["mean"],
        "recompiles": it["recompiles"]["traces"],
        "backend_compiles": it["recompiles"]["backend_compiles"],
        "warm_iterations": it["recompiles"]["warm_iterations"],
        "iterations": it["count"],
        "num_evals": end.get("num_evals"),
        "elapsed_s": end.get("elapsed_s"),
        "stop_reason": end.get("stop_reason"),
        # graftpulse: detector excursions in this run. Rides into the
        # bench artifacts via extract.py (extra metrics_view keys are
        # carried along) and colors `bench trend`'s anomalies column.
        "anomalies": (summary.get("anomalies") or {}).get("count", 0),
        # graftgauge: peak live-array bytes the run reached (None for
        # pre-gauge streams / gauge off); rides into bench cells the
        # same way and shows in `bench trend`.
        "peak_live_bytes": (summary.get("gauge")
                            or {}).get("peak_live_bytes"),
    }


def _fmt_pct(x: Optional[float]) -> str:
    return "-" if x is None else f"{100.0 * x:.1f}%"


def _fmt_num(x: Optional[float]) -> str:
    if x is None:
        return "-"
    return f"{x:,.3g}" if isinstance(x, float) else f"{x:,}"


def format_report(summary: Dict[str, Any]) -> str:
    """Human-readable text report."""
    lines: List[str] = []
    run = summary.get("run", {})
    if run:
        lines.append(
            f"run {run.get('run_id')}  [{run.get('backend')} x "
            f"{run.get('n_devices')} device(s), nout={run.get('nout')}, "
            f"interval={run.get('telemetry_interval')}]"
        )
    it = summary["iterations"]
    eps = it["evals_per_sec"]
    lines.append(
        f"iterations: {it['count']} events  |  evals/s final "
        f"{_fmt_num(eps['final'])}, peak {_fmt_num(eps['peak'])}"
    )
    curve = eps["curve"]
    if len(curve) > 1:
        pts = ", ".join(f"{i}:{_fmt_num(v)}" for i, v in curve[:12])
        more = "" if len(curve) <= 12 else f", ... +{len(curve) - 12}"
        lines.append(f"  evals/s trajectory: {pts}{more}")
    hf = it["host_fraction"]
    lines.append(
        f"host-fraction: mean {_fmt_pct(hf['mean'])}, max "
        f"{_fmt_pct(hf['max'])}, final {_fmt_pct(hf['final'])}"
    )
    rc = it["recompiles"]
    lines.append(
        f"recompiles: {rc['traces']} traces / {rc['backend_compiles']} "
        f"backend compiles across iteration events "
        f"({rc['warm_iterations']} warm iterations); "
        f"{it['transfer_guard_hits']} transfer-guard hits"
    )
    for out in summary["outputs"]:
        lines.append(f"output {out['output']}:")
        pv = out["pareto_volume_curve"]
        if pv:
            pts = ", ".join(f"{i}:{v:.3g}" for i, v in pv[:12])
            more = "" if len(pv) <= 12 else f", ... +{len(pv) - 12}"
            lines.append(f"  pareto volume: {pts}{more}")
        if out.get("final_min_loss") is not None:
            lines.append(f"  final min loss: {out['final_min_loss']:.6g}")
        if "acceptance_rate" in out:
            rates = sorted(
                out["acceptance_rate"].items(), key=lambda kv: -kv[1]
            )
            lines.append("  acceptance by kind (accepted/proposed):")
            for k, r in rates:
                lines.append(
                    f"    {k:<18} {out['accepted'][k]:>8,} / "
                    f"{out['proposed'][k]:>8,}  ({_fmt_pct(r)})"
                )
            lines.append(
                f"  candidates: {_fmt_num(out['candidates'])}  "
                f"(invalid {_fmt_pct(out['invalid_fraction'])})  |  "
                f"eval rows {_fmt_num(out['eval_rows'])} in "
                f"{_fmt_num(out['eval_launches'])} launches  |  "
                f"dedup hit-rate {_fmt_pct(out['dedup_hit_rate'])}"
            )
            if out.get("screen_rows"):
                lines.append(
                    f"  staged eval: screened "
                    f"{_fmt_num(out['screen_rows'])}, rescored "
                    f"{_fmt_num(out['rescore_rows'])}  "
                    f"(observed rescore fraction "
                    f"{_fmt_pct(out['observed_rescore_fraction'])}, "
                    f"rescored-invalid "
                    f"{_fmt_pct(out.get('rescored_invalid_fraction'))})"
                )
            rej = out.get("reject_reasons", {})
            if rej:
                lines.append(
                    "  reject reasons: "
                    + ", ".join(f"{k}={v:,}" for k, v in rej.items())
                )
    fl = summary.get("faults")
    if fl:
        kinds = ", ".join(
            f"{k}={v}" for k, v in sorted(fl.get("by_kind", {}).items())
        )
        lines.append(
            f"faults: {fl.get('count', 0)} event(s)"
            + (f"  ({kinds})" if kinds else "")
        )
        for it_n, kind in fl.get("timeline", [])[:12]:
            lines.append(f"  iter {it_n}: {kind}")
    an = summary.get("anomalies")
    if an and an.get("count"):
        kinds = ", ".join(
            f"{k}={v}" for k, v in sorted(an.get("by_metric", {}).items())
        )
        lines.append(
            f"anomalies: {an['count']} event(s)"
            + (f"  ({kinds})" if kinds else "")
        )
        for it_n, metric in an.get("timeline", [])[:12]:
            lines.append(f"  iter {it_n}: {metric}")
    pu = summary.get("pulse")
    if pu:
        lines.append(
            f"pulse: {pu['captures']} profiler capture(s), "
            f"{pu['bundles']} bundle dump(s)  ("
            + ", ".join(f"{k}={v}"
                        for k, v in sorted(pu["by_kind"].items()))
            + ")"
        )
    ga = summary.get("gauge")
    if ga:
        lines.append(
            f"gauge: peak live {_fmt_num(ga.get('peak_live_bytes'))} B  ("
            + ", ".join(f"{k}={v}"
                        for k, v in sorted(ga["by_kind"].items()))
            + ")"
        )
        dl = ga.get("dispatch_latency")
        if dl and dl.get("count"):
            lines.append(
                f"  dispatch latency: {dl['count']} launches, "
                f"p50 {_fmt_num(dl.get('p50_s'))}s, "
                f"p99 {_fmt_num(dl.get('p99_s'))}s, "
                f"max {_fmt_num(dl.get('max_s'))}s"
            )
        if ga.get("footprints"):
            lines.append(
                f"  footprints: {ga['footprints']} compiled program(s), "
                f"largest {_fmt_num(ga.get('footprint_max_bytes'))} B"
            )
    ms = summary.get("mesh")
    if ms:
        lines.append(
            f"mesh: {ms['exchanges']} dedup-key exchange(s) over "
            f"{ms.get('shards')} shard(s)  |  dup local "
            f"{_fmt_pct(ms['local_dup_fraction'])} / cross-shard "
            f"{_fmt_pct(ms['cross_shard_dup_fraction'])}"
            f"  |  imbalance {ms.get('last_shard_imbalance')}"
            f"  |  {_fmt_num(ms['exchanged_bytes_total'])} B in "
            f"{ms['exchange_time_s_total']:.3f}s"
            + ("" if ms.get("sharded_dedup") else
               "  [sharded dedup OFF]")
        )
    sv = summary.get("serve")
    if sv:
        cache = sv["cache"]
        lines.append(
            f"serve: {sv['accepted']} accepted, {sv['rejected']} rejected"
            f"  |  executable cache {cache['hits']} hit / "
            f"{cache['misses']} miss ({_fmt_pct(cache['hit_rate'])})"
        )
        other = {k: v for k, v in sorted(sv["by_kind"].items())
                 if k not in ("accept", "reject")}
        if other:
            lines.append(
                "  events: " + ", ".join(f"{k}={v}" for k, v in other.items())
            )
        pk = sv.get("packing")
        if pk:
            lines.append(
                f"  packing: {pk['launches']} launch(es) "
                f"({pk['multi_tenant_launches']} multi-tenant, "
                f"{pk['tenants']} tenant(s))  |  "
                f"{pk['padded_accepts']} padded accept(s), "
                f"{pk['pad_rows_total']} pad rows  |  "
                f"occupancy {pk['mean_occupancy']}"
            )
    reqs = summary.get("requests")
    if reqs:
        lines.append(
            f"requests: {len(reqs)} "
            "(grouped by trace_id, else request_id/run_id)")
        for rid in sorted(reqs):
            g = reqs[rid]
            bits = []
            if g.get("state"):
                bits.append(g["state"])
            if g.get("stop_reason"):
                bits.append(f"stop={g['stop_reason']}")
            if g.get("iterations"):
                bits.append(f"iters={g['iterations']}")
            if g.get("num_evals") is not None:
                bits.append(f"evals={_fmt_num(g['num_evals'])}")
            if g.get("faults"):
                bits.append(
                    "faults["
                    + ",".join(f"{k}={v}"
                               for k, v in sorted(g["faults"].items()))
                    + "]"
                )
            if g.get("serve", {}).get("cache_hit"):
                bits.append("cache-hit")
            if g.get("padding"):
                bits.append(
                    f"padded+{g['padding'].get('pad_rows')}"
                    f"->{g['padding'].get('bucket_rows')}")
            if g.get("span_s") is not None:
                bits.append(f"{g['span_s']:.1f}s")
            lines.append(f"  {rid}: " + (", ".join(bits) or "no activity"))
    end = summary.get("end")
    if end:
        lines.append(
            f"run end: {end.get('stop_reason')} after "
            f"{end.get('iterations')} iterations, "
            f"{_fmt_num(end.get('num_evals'))} evals in "
            f"{_fmt_num(end.get('elapsed_s'))}s; lifetime compiles "
            f"{end.get('recompiles_total')}"
        )
    return "\n".join(lines)


_USAGE = """usage: python -m symbolicregression_jl_tpu.telemetry <cmd> <run.jsonl>

commands:
  report <run.jsonl> [--json]      summarize a run (refuses invalid files)
  report <run.jsonl> --metrics     flat gate-metrics JSON (graftbench view)
  validate <run.jsonl>             check every line against graftscope.v1
  tail <run.jsonl> [--interval S]  follow a live stream with a refreshing
       [--once]                    single-screen summary (--once: one shot)
  timeline <root> --out <t.json>   merge a serve root's journal, request
                                   streams and cost ledgers into one
                                   Chrome trace-event file (Perfetto /
                                   chrome://tracing openable)

report tolerates a torn final line (the crash artifact of a killed
writer): it is skipped and counted on stderr, like journal replay.
"""


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(_USAGE, end="")
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "validate":
        if len(rest) != 1:
            print(_USAGE, end="", file=sys.stderr)
            return 2
        with open(rest[0]) as f:
            errors = validate_lines(f.readlines())
        if errors:
            for e in errors:
                print(e, file=sys.stderr)
            print(f"{rest[0]}: {len(errors)} violation(s)", file=sys.stderr)
            return 1
        print(f"{rest[0]}: valid {SCHEMA_VERSION}")
        return 0
    if cmd == "report":
        as_json = "--json" in rest
        as_metrics = "--metrics" in rest
        paths = [a for a in rest if not a.startswith("-")]
        if len(paths) != 1:
            print(_USAGE, end="", file=sys.stderr)
            return 2
        try:
            events = load_events(paths[0])
        except ValueError:
            # skip-and-count fallback (journal-replay idiom): a torn
            # tail — the expected artifact of a crashed/killed writer —
            # must not make the rest of the stream unreadable. Any
            # OTHER bad line still refuses: mid-file corruption means
            # records may be missing, and a silently partial report
            # would misrepresent the run.
            events, notes = load_events_tolerant(paths[0])
            hard = [n for n in notes if not n["torn_tail"]]
            if hard or not events:
                for n in notes:
                    print(f"line {n['line']}: {n['reason']}",
                          file=sys.stderr)
                print(f"{paths[0]}: unreadable ({len(notes)} bad line(s), "
                      f"{len(hard)} before the tail)", file=sys.stderr)
                return 1
            for n in notes:
                print(f"warning: skipped torn line {n['line']}: "
                      f"{n['reason']}", file=sys.stderr)
        summary = summarize(events)
        if as_metrics:
            print(json.dumps(metrics_view(summary)))
        elif as_json:
            print(json.dumps(summary))
        else:
            print(format_report(summary))
        return 0
    if cmd == "tail":
        from .tail import main as tail_main

        return tail_main(rest)
    if cmd == "timeline":
        from ..ledger.timeline import write_timeline

        out = None
        paths = []
        i = 0
        while i < len(rest):
            if rest[i] == "--out":
                if i + 1 >= len(rest):
                    print(_USAGE, end="", file=sys.stderr)
                    return 2
                out = rest[i + 1]
                i += 2
            elif rest[i].startswith("-"):
                print(_USAGE, end="", file=sys.stderr)
                return 2
            else:
                paths.append(rest[i])
                i += 1
        if len(paths) != 1 or not out:
            print(_USAGE, end="", file=sys.stderr)
            return 2
        doc = write_timeline(paths[0], out)
        n = len(doc.get("traceEvents", []))
        if n == 0:
            print(f"{paths[0]}: no telemetry found", file=sys.stderr)
            return 1
        print(f"{out}: {n} trace events")
        return 0
    print(_USAGE, end="", file=sys.stderr)
    return 2
