"""Device-side search telemetry accumulators.

The evolve scan (evolve/step.py `s_r_cycle`) runs entirely inside one
XLA program per iteration; everything that happens in it — which
mutation kinds get sampled, how many candidates survive constraints and
annealing, how many evals produce non-finite losses — is invisible to
the host unless it is accumulated *in-graph*. These counters are small
int32 vectors carried through the scan (`CycleTelemetry`), summed over
islands in the iteration epilogue, and stored on the engine state
(`IterationTelemetry` on ``SearchDeviceState.telem``), so the host
fetches them with the same per-iteration state pull it already performs:
the hot loop stays at 0 extra dispatches, 0 extra transfers, 0 retraces
(pinned by tests/test_hot_loop_guards.py with telemetry enabled).

Counters are PER ITERATION (reset in-graph at each iteration start, not
cumulative): int32 cannot overflow within one iteration at any plausible
config, and the host-side `Telemetry` hub does the cross-iteration
accumulation in Python ints.

Counter semantics (schema `graftscope.v1`, docs/OBSERVABILITY.md):

- ``proposed[k]`` — generation-step slots whose sampled operation was
  mutation kind ``k`` (index order = ``MUTATION_KINDS``; the last index
  is crossover). One proposal per slot per cycle.
- ``accepted[k]`` — proposals that replaced a member with the *new*
  genome (mutations: passed constraints + finite cost + annealing;
  immediate kinds count as accepted, matching the reference's
  return_immediately contract; crossover: both-children-valid
  replacement). Kept-parent fallbacks (skip_mutation_failures=False) are
  NOT accepts.
- ``reject_reasons[r]`` — slot-level rejection reason histogram, codes
  matching `CycleEvents.reject_reason` (0 none, 1 constraint/no-valid-
  candidate, 2 non-finite cost, 3 annealing/frequency rejection).
- ``candidates`` — candidate evals actually needed (the raw
  ``num_evals`` increments, before any minibatch fraction scaling).
- ``invalid`` — needed candidates whose evaluated cost came back
  non-finite (NaN/inf loss); ``invalid / candidates`` is the
  invalid-candidate fraction.
- ``eval_rows`` / ``eval_launches`` — rows through / launches of the
  candidate-eval kernel (per island in the cycle part; the iteration
  epilogue adds the finalize re-eval).
- ``screen_rows`` / ``screen_launches`` / ``rescore_rows`` /
  ``rescore_launches`` — graftstage staged-eval counters
  (docs/PRECISION.md): candidates through / launches of the sampled
  screening pass and the full-row rescore pass. All zero when staging
  is off; when it is on, ``rescore_rows / screen_rows`` is the observed
  rescore fraction (graftpulse's drift rule compares it against the
  configured ``rescore_fraction``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.options import MUTATION_KINDS

__all__ = [
    "N_KIND_SLOTS",
    "N_REASONS",
    "LOSS_HIST_BINS",
    "LOSS_HIST_LO",
    "LOSS_HIST_HI",
    "CycleTelemetry",
    "IterationTelemetry",
    "empty_cycle_telemetry",
    "empty_iteration_telemetry",
    "step_telemetry",
    "add_cycle_telemetry",
    "member_dup_stats",
    "member_hash_keys",
    "unique_key_count",
    "loss_histogram",
]

# Mutation kinds + 1 crossover pseudo-kind (same convention as
# CycleEvents.kind in evolve/step.py).
N_KIND_SLOTS = len(MUTATION_KINDS) + 1
N_REASONS = 4  # none / constraint / invalid / annealing

# Population-loss histogram: log10(loss) bins over [LO, HI); finite
# losses <= 0 (perfect fits) clamp into the first bin, non-finite losses
# are not counted.
LOSS_HIST_BINS = 32
LOSS_HIST_LO = -8.0
LOSS_HIST_HI = 8.0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CycleTelemetry:
    """Per-cycle counters accumulated in the evolve scan carry.

    Shapes are leading-axis-free here; the engine vmaps one instance per
    island ([I, ...]) and sums over islands in the epilogue."""

    proposed: jax.Array        # [N_KIND_SLOTS] int32
    accepted: jax.Array        # [N_KIND_SLOTS] int32
    reject_reasons: jax.Array  # [N_REASONS] int32
    candidates: jax.Array      # [] int32
    invalid: jax.Array         # [] int32
    eval_rows: jax.Array       # [] int32
    eval_launches: jax.Array   # [] int32
    screen_rows: jax.Array     # [] int32 (staged eval only, else 0)
    screen_launches: jax.Array   # [] int32
    rescore_rows: jax.Array      # [] int32
    rescore_launches: jax.Array  # [] int32


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class IterationTelemetry:
    """One iteration's telemetry, stored on ``SearchDeviceState.telem``.

    ``finalize_rows`` / ``finalize_unique`` measure structural member
    duplication in the finalize re-eval batch — the duplication the
    fused dedup path exploits (``finalize_rows - finalize_unique`` =
    dedup hits). The legacy engine reports zeros when the island axis
    is sharded (dedup disabled there; a global sort would need
    per-iteration collectives); the mesh runtime (mesh/engine.py)
    reports the psum of PER-SHARD stats instead — exactly what its
    per-shard dedup exploits."""

    cycle: CycleTelemetry
    finalize_rows: jax.Array     # [] int32
    finalize_unique: jax.Array   # [] int32
    loss_hist: jax.Array         # [LOSS_HIST_BINS] int32
    cx_hist: jax.Array           # [maxsize] int32


def empty_cycle_telemetry() -> CycleTelemetry:
    z = jnp.int32(0)
    return CycleTelemetry(
        proposed=jnp.zeros((N_KIND_SLOTS,), jnp.int32),
        accepted=jnp.zeros((N_KIND_SLOTS,), jnp.int32),
        reject_reasons=jnp.zeros((N_REASONS,), jnp.int32),
        candidates=z,
        invalid=z,
        eval_rows=z,
        eval_launches=z,
        screen_rows=z,
        screen_launches=z,
        rescore_rows=z,
        rescore_launches=z,
    )


def empty_iteration_telemetry(maxsize: int) -> IterationTelemetry:
    z = jnp.int32(0)
    return IterationTelemetry(
        cycle=empty_cycle_telemetry(),
        finalize_rows=z,
        finalize_unique=z,
        loss_hist=jnp.zeros((LOSS_HIST_BINS,), jnp.int32),
        cx_hist=jnp.zeros((maxsize,), jnp.int32),
    )


def add_cycle_telemetry(a: CycleTelemetry, b: CycleTelemetry) -> CycleTelemetry:
    return jax.tree.map(lambda x, y: x + y, a, b)


def step_telemetry(
    *,
    kind: jax.Array,          # [B] int32 sampled mutation kind
    is_xover: jax.Array,      # [B] bool
    immediate: jax.Array,     # [B] bool
    accepted_mut: jax.Array,  # [B] bool
    xo_replace: jax.Array,    # [B] bool
    mut_success: jax.Array,   # [B] bool
    xo_success: jax.Array,    # [B] bool
    after_cost: jax.Array,    # [B] candidate-1 cost
    xo_nan: jax.Array,        # [B] bool either crossover child non-finite
    anneal_ok: jax.Array,     # [B] bool
    cost: jax.Array,          # [B, 2] both babies' costs
    needs_eval1: jax.Array,   # [B] bool
    needs_eval2: jax.Array,   # [B] bool
    n_eval_rows: int,         # static rows in this step's eval launch
    n_screen_rows: int = 0,   # static candidates screened (staged eval)
    n_rescore_rows: int = 0,  # static candidates rescored (staged eval)
) -> CycleTelemetry:
    """Counters for one generation step, from values the step already
    computed (no extra RNG draws, no change to the search dataflow — the
    telemetry=on/off search trajectories are bit-identical)."""
    nk = len(MUTATION_KINDS)
    k_eff = jnp.where(is_xover, jnp.int32(nk), kind).astype(jnp.int32)
    oh = jax.nn.one_hot(k_eff, N_KIND_SLOTS, dtype=jnp.int32)  # [B, NK+1]
    proposed = jnp.sum(oh, axis=0)
    acc = jnp.where(is_xover, xo_replace, immediate | accepted_mut)
    accepted = jnp.sum(oh * acc.astype(jnp.int32)[:, None], axis=0)

    # Same reject-reason chain as CycleEvents (evolve/step.py): shared
    # semantics so the recorder's aggregate counts and these counters
    # can never disagree on what "invalid" means.
    mut_reason = jnp.where(
        ~mut_success, 1,
        jnp.where(~jnp.isfinite(after_cost), 2,
                  jnp.where(~anneal_ok, 3, 0)))
    xo_reason = jnp.where(~xo_success, 1, jnp.where(xo_nan, 2, 0))
    reason = jnp.where(
        is_xover, xo_reason, jnp.where(immediate, 0, mut_reason)
    ).astype(jnp.int32)
    reject_reasons = jnp.sum(
        jax.nn.one_hot(reason, N_REASONS, dtype=jnp.int32), axis=0)

    inv = (
        jnp.sum((needs_eval1 & ~jnp.isfinite(cost[:, 0])).astype(jnp.int32))
        + jnp.sum((needs_eval2 & ~jnp.isfinite(cost[:, 1])).astype(jnp.int32))
    )
    cands = (jnp.sum(needs_eval1.astype(jnp.int32))
             + jnp.sum(needs_eval2.astype(jnp.int32)))
    return CycleTelemetry(
        proposed=proposed,
        accepted=accepted,
        reject_reasons=reject_reasons,
        candidates=cands,
        invalid=inv,
        eval_rows=jnp.int32(n_eval_rows),
        eval_launches=jnp.int32(2 if n_screen_rows else 1),
        screen_rows=jnp.int32(n_screen_rows),
        screen_launches=jnp.int32(1 if n_screen_rows else 0),
        rescore_rows=jnp.int32(n_rescore_rows),
        rescore_launches=jnp.int32(1 if n_rescore_rows else 0),
    )


# ---------------------------------------------------------------------------
# Member duplication stats (the dedup hit-rate counter)
# ---------------------------------------------------------------------------

# Fixed odd multipliers for 3 independent linear int32-wraparound hashes
# (same technique as ops/fused_eval's dedup adjacency hash; collisions
# over the 3x31-bit combined key are negligible at population scales —
# telemetry-grade exactness). Module-level fixed-seed constant,
# deterministic by construction — not search RNG.
@functools.lru_cache(maxsize=8)
def _dup_hash_consts(width: int) -> np.ndarray:
    rng = np.random.default_rng(0x5C09E)  # graftlint: disable=GL002
    return (rng.integers(1, 2**31, size=(3, width), dtype=np.int64)
            .astype(np.int32) | 1)


def member_hash_keys(trees) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Three independent [N] int32 member-identity hash keys over the
    member axes of a TreeBatch ([I, P, L] or template [I, P, K, L]):
    structurally identical members (constants included) hash to the same
    3-key tuple. Shared by :func:`member_dup_stats` and the mesh
    runtime's cross-shard dedup-key exchange (mesh/engine.py), so the
    two can never disagree on what "duplicate" means."""
    L = trees.arity.shape[-1]
    I, P = trees.arity.shape[0], trees.arity.shape[1]
    N = I * P
    lane = jnp.arange(L) < trees.length[..., None]
    word = jnp.where(
        lane,
        (trees.arity.astype(jnp.int32) << 28)
        ^ (trees.op.astype(jnp.int32) << 20)
        ^ (trees.feat.astype(jnp.int32) << 8),
        0,
    )
    cbits = jnp.where(
        lane,
        jax.lax.bitcast_convert_type(
            trees.const.astype(jnp.float32), jnp.int32),
        0,
    )
    word2 = word.reshape(N, -1)
    cbits2 = cbits.reshape(N, -1)
    W = word2.shape[1]
    R = jnp.asarray(_dup_hash_consts(2 * W))
    k0, k1, k2 = (
        jnp.sum(word2 * R[k, :W][None, :]
                + cbits2 * R[k, W:][None, :], axis=1)
        for k in range(3)
    )
    return k0, k1, k2


def unique_key_count(keys) -> jax.Array:
    """Number of distinct 3-key tuples among ``keys`` (three [N] int32
    arrays): one ``lax.sort`` + neighbor comparison."""
    sorted_keys = jax.lax.sort(list(keys), dimension=0, num_keys=3)
    prev = lambda x: jnp.concatenate([x[:1], x[:-1]])
    differs = jnp.zeros(sorted_keys[0].shape, jnp.bool_)
    for k in sorted_keys:
        differs = differs | (k != prev(k))
    return jnp.int32(1) + jnp.sum(differs.astype(jnp.int32))


def member_dup_stats(trees) -> Tuple[jax.Array, jax.Array]:
    """(rows, unique) over the member axes of a TreeBatch ([I, P, L] or
    template [I, P, K, L]): how many member rows are structurally
    identical copies (constants included). This is the duplication the
    fused dedup eval exploits at finalize (profiling/dup_rate.py
    measured ~50% at the bench config); ``rows - unique`` = dedup hits.

    Cost: two tiny [N] int32 hash reductions + one ``lax.sort`` of three
    [N] keys — noise next to the finalize eval itself. Hash-only count:
    a 93-bit collision would undercount uniques by 1; acceptable for a
    telemetry counter (the dedup kernel itself verifies exactly).
    """
    keys = member_hash_keys(trees)
    N = trees.arity.shape[0] * trees.arity.shape[1]
    return jnp.int32(N), unique_key_count(keys)


def loss_histogram(loss: jax.Array) -> jax.Array:
    """[LOSS_HIST_BINS] int32 histogram of log10(loss) over finite
    population losses (finite losses <= 0 land in bin 0)."""
    flat = loss.reshape(-1)
    finite = jnp.isfinite(flat)
    lg = jnp.log10(jnp.maximum(jnp.where(finite, flat, 1.0), 1e-30))
    idx = jnp.clip(
        ((lg - LOSS_HIST_LO)
         / (LOSS_HIST_HI - LOSS_HIST_LO) * LOSS_HIST_BINS).astype(jnp.int32),
        0, LOSS_HIST_BINS - 1,
    )
    oh = jax.nn.one_hot(idx, LOSS_HIST_BINS, dtype=jnp.int32)
    return jnp.sum(oh * finite.astype(jnp.int32)[:, None], axis=0)
