"""Span tracing: line a profiler capture up with search iterations.

Thin wrappers over ``jax.profiler``'s trace annotations, named so a
perfetto / xplane capture of a search shows one ``sr:iteration`` step
per engine iteration with the host phases (hall-of-fame decode,
checkpoint CSV writes, telemetry sinks/logging) as named spans between
device steps. Annotations are no-ops (nanoseconds of overhead) when no
trace is being captured, so they are ALWAYS on — no option gates them.

Span names (schema-stable, see docs/OBSERVABILITY.md):

- ``sr:iteration`` — ``StepTraceAnnotation`` per search iteration
  (device launches + the blocking sync), carrying ``step_num``.
- ``sr:host:hof_decode`` — device HoF pull + host tree decode.
- ``sr:host:checkpoint`` — hall-of-fame CSV + full-state pickle writes.
- ``sr:host:sinks`` — telemetry hub sink dispatch (SRLogger, Recorder,
  ProgressBar, JSONL emission).
- ``sr:host:report`` — regressor report building (pareto scoring,
  equation stringification).
"""

from __future__ import annotations

import contextlib

__all__ = ["step_span", "host_span"]


def step_span(step_num: int):
    """Profiler step annotation for one search iteration."""
    try:
        import jax.profiler as _prof

        return _prof.StepTraceAnnotation("sr:iteration", step_num=step_num)
    except Exception:  # pragma: no cover - profiler unavailable
        return contextlib.nullcontext()


def host_span(name: str):
    """Named host-phase span (``sr:host:<name>``)."""
    try:
        import jax.profiler as _prof

        return _prof.TraceAnnotation(f"sr:host:{name}")
    except Exception:  # pragma: no cover - profiler unavailable
        return contextlib.nullcontext()
