"""Span tracing: line a profiler capture up with search iterations.

Thin wrappers over ``jax.profiler``'s trace annotations, named so a
perfetto / xplane capture of a search shows one ``sr:iteration`` step
per engine iteration with the host phases (hall-of-fame decode,
checkpoint CSV writes, telemetry sinks/logging) as named spans between
device steps. Annotations are no-ops (nanoseconds of overhead) when no
trace is being captured, so they are ALWAYS on — no option gates them.

Span names (schema-stable, see docs/OBSERVABILITY.md):

- ``sr:iteration`` — ``StepTraceAnnotation`` per search iteration
  (device launches + the blocking sync), carrying ``step_num``.
- ``sr:host:hof_decode`` — device HoF pull + host tree decode.
- ``sr:host:checkpoint`` — hall-of-fame CSV + full-state pickle writes.
- ``sr:host:sinks`` — telemetry hub sink dispatch (SRLogger, Recorder,
  ProgressBar, JSONL emission).
- ``sr:host:report`` — regressor report building (pareto scoring,
  equation stringification).

Failure discipline: an unusable ``jax.profiler`` must never break the
search (spans degrade to ``nullcontext``), but it must not be SILENT
either — an operator staring at an empty graftpulse trace needs to know
the annotations never existed. The first failure per process reports
through the hook the telemetry hub registers (a one-time ``pulse``
event, kind ``profiler_unusable``); later failures stay quiet.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Optional

__all__ = ["step_span", "host_span", "set_profiler_warning_hook",
           "set_span_observer"]

# graftledger host-phase observer: a thread-local callback receiving
# (name, seconds) for every completed host span on THIS thread. Thread-
# local because a SearchServer runs concurrent searches on concurrent
# worker threads — each search's ledger must see only its own phases.
# When no observer is registered (ledger off, or any thread that never
# set one) host_span returns the raw annotation unchanged: zero new
# work on the hot path.
_observer = threading.local()


def set_span_observer(
        fn: Optional[Callable[[str, float], None]]) -> None:
    """Register (or clear, with None) this thread's host-span observer.
    The cost ledger registers one for the search's lifetime and clears
    it in the loop's ``finally``."""
    _observer.fn = fn


class _TimedSpan:
    """Wraps a profiler annotation with a wall-clock timing report."""

    __slots__ = ("name", "inner", "report", "_t0")

    def __init__(self, name: str, inner,
                 report: Callable[[str, float], None]) -> None:
        self.name = name
        self.inner = inner
        self.report = report

    def __enter__(self):
        self._t0 = time.perf_counter()
        self.inner.__enter__()
        return self

    def __exit__(self, *exc):
        result = self.inner.__exit__(*exc)
        try:
            self.report(self.name, time.perf_counter() - self._t0)
        except Exception:  # observation must never outcrash the span
            pass
        return result

# one-time-per-process profiler-unusable warning plumbing: the latest
# constructed Telemetry hub owns the hook (multiple hubs in one process
# all funnel to whichever registered last — the warning is about the
# PROCESS's profiler, not one run)
_warn_hook: Optional[Callable[[str], None]] = None
_warned = False


def set_profiler_warning_hook(hook: Optional[Callable[[str], None]]) -> None:
    """Register the callback invoked (once per process) when a span is
    requested but ``jax.profiler`` is unusable. The telemetry hub passes
    a closure emitting a ``pulse`` event, kind ``profiler_unusable``."""
    global _warn_hook
    _warn_hook = hook


def _note_profiler_unusable(err: BaseException) -> None:
    global _warned
    if _warned:
        return
    _warned = True
    hook = _warn_hook
    if hook is None:
        return
    try:
        hook(f"{type(err).__name__}: {err}")
    except Exception:  # the warning must never outcrash the no-op
        pass


def step_span(step_num: int, *, trace_id: Optional[str] = None,
              span_id: Optional[str] = None):
    """Profiler step annotation for one search iteration.

    When graftledger trace context is threaded in, the annotation
    carries ``trace_id``/``span_id`` attributes so an on-device
    profiler capture (perfetto/xplane) correlates with the host
    timeline and the JSONL streams by id, not by eyeballing clocks.
    """
    try:
        import jax.profiler as _prof

        attrs = {"step_num": step_num}
        if trace_id is not None:
            attrs["trace_id"] = trace_id
        if span_id is not None:
            attrs["span_id"] = span_id
        return _prof.StepTraceAnnotation("sr:iteration", **attrs)
    except Exception as e:  # pragma: no cover - profiler unavailable
        _note_profiler_unusable(e)
        return contextlib.nullcontext()


def host_span(name: str):
    """Named host-phase span (``sr:host:<name>``); timed and reported
    to this thread's ledger observer when one is registered."""
    try:
        import jax.profiler as _prof

        span = _prof.TraceAnnotation(f"sr:host:{name}")
    except Exception as e:  # pragma: no cover - profiler unavailable
        _note_profiler_unusable(e)
        span = contextlib.nullcontext()
    fn = getattr(_observer, "fn", None)
    if fn is None:
        return span
    return _TimedSpan(name, span, fn)
