"""Span tracing: line a profiler capture up with search iterations.

Thin wrappers over ``jax.profiler``'s trace annotations, named so a
perfetto / xplane capture of a search shows one ``sr:iteration`` step
per engine iteration with the host phases (hall-of-fame decode,
checkpoint CSV writes, telemetry sinks/logging) as named spans between
device steps. Annotations are no-ops (nanoseconds of overhead) when no
trace is being captured, so they are ALWAYS on — no option gates them.

Span names (schema-stable, see docs/OBSERVABILITY.md):

- ``sr:iteration`` — ``StepTraceAnnotation`` per search iteration
  (device launches + the blocking sync), carrying ``step_num``.
- ``sr:host:hof_decode`` — device HoF pull + host tree decode.
- ``sr:host:checkpoint`` — hall-of-fame CSV + full-state pickle writes.
- ``sr:host:sinks`` — telemetry hub sink dispatch (SRLogger, Recorder,
  ProgressBar, JSONL emission).
- ``sr:host:report`` — regressor report building (pareto scoring,
  equation stringification).

Failure discipline: an unusable ``jax.profiler`` must never break the
search (spans degrade to ``nullcontext``), but it must not be SILENT
either — an operator staring at an empty graftpulse trace needs to know
the annotations never existed. The first failure per process reports
through the hook the telemetry hub registers (a one-time ``pulse``
event, kind ``profiler_unusable``); later failures stay quiet.
"""

from __future__ import annotations

import contextlib
from typing import Callable, Optional

__all__ = ["step_span", "host_span", "set_profiler_warning_hook"]

# one-time-per-process profiler-unusable warning plumbing: the latest
# constructed Telemetry hub owns the hook (multiple hubs in one process
# all funnel to whichever registered last — the warning is about the
# PROCESS's profiler, not one run)
_warn_hook: Optional[Callable[[str], None]] = None
_warned = False


def set_profiler_warning_hook(hook: Optional[Callable[[str], None]]) -> None:
    """Register the callback invoked (once per process) when a span is
    requested but ``jax.profiler`` is unusable. The telemetry hub passes
    a closure emitting a ``pulse`` event, kind ``profiler_unusable``."""
    global _warn_hook
    _warn_hook = hook


def _note_profiler_unusable(err: BaseException) -> None:
    global _warned
    if _warned:
        return
    _warned = True
    hook = _warn_hook
    if hook is None:
        return
    try:
        hook(f"{type(err).__name__}: {err}")
    except Exception:  # the warning must never outcrash the no-op
        pass


def step_span(step_num: int):
    """Profiler step annotation for one search iteration."""
    try:
        import jax.profiler as _prof

        return _prof.StepTraceAnnotation("sr:iteration", step_num=step_num)
    except Exception as e:  # pragma: no cover - profiler unavailable
        _note_profiler_unusable(e)
        return contextlib.nullcontext()


def host_span(name: str):
    """Named host-phase span (``sr:host:<name>``)."""
    try:
        import jax.profiler as _prof

        return _prof.TraceAnnotation(f"sr:host:{name}")
    except Exception as e:  # pragma: no cover - profiler unavailable
        _note_profiler_unusable(e)
        return contextlib.nullcontext()
