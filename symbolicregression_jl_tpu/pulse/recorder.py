"""The flight recorder: last-K-iterations evidence, dumped on faults.

A bounded in-memory ring of the most recent iterations (device
counters + host timings) and out-of-band events, held by the process
that is about to die or go wrong. Registered with the telemetry hub
twice — as a sink (``on_iteration``) for the per-iteration view and as
a watcher (``on_event``) for fault/mesh/anomaly/pulse events — so a
shield watchdog timeout, an island quarantine, or an injected fault
triggers a bundle dump BEFORE the watchdog's process abort
(``os._exit(124)``) can discard the evidence; the search loop dumps
once more from its ``finally`` when the run is exiting on an error.

Bundle layout (``graftpulse.bundle.v1``, one JSON object):

- everything OUTSIDE the ``wall`` subtree is deterministic given the
  seed and fault plan — iteration numbers, eval counts, device
  counters, the (event, kind, iteration) timeline — which is what makes
  the dump byte-stable across two identical runs (pinned in
  tests/test_pulse.py) and therefore diffable;
- ``wall`` holds everything wall-clock: timings, rates, and the full
  raw events (whose details may carry elapsed times and paths).

``bundle_fingerprint`` hashes the deterministic view; two runs of the
same plan produce the same fingerprint.
"""

from __future__ import annotations

import collections
import hashlib
import json
import math
import os
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "BUNDLE_SCHEMA",
    "FlightRecorder",
    "bundle_fingerprint",
    "deterministic_view",
    "validate_bundle",
]

BUNDLE_SCHEMA = "graftpulse.bundle.v1"

# event types whose arrival triggers a dump (the "something is wrong"
# funnel — every shield recovery path emits a fault event)
_DUMP_TRIGGERS = ("fault",)

# anomaly metrics that ALSO trigger a dump: most anomalies arm a
# profiler capture instead (slow != dying), but the graftgauge leak
# tripwire wants the bundle — its deterministic memory snapshots ARE
# the leak evidence, and a leak that later OOMs may take the process
# with it before any fault event fires
_DUMP_ANOMALY_METRICS = ("live_bytes_growth",)


def _finite(x) -> Optional[float]:
    try:
        v = float(x)
    except (TypeError, ValueError):
        return None
    return v if math.isfinite(v) else None


class FlightRecorder:
    """Bounded ring of recent iterations + events; see module docstring.

    Host-side only: every value recorded was already materialized by
    the search loop or the hub — no device access, no extra transfers,
    nothing fed back into the search (bit-neutral by construction).
    """

    def __init__(
        self,
        *,
        capacity: int = 32,
        path: Optional[str] = None,
        run_id: str = "",
        hub=None,
        event_capacity: int = 64,
        max_dumps: int = 16,
    ) -> None:
        self.capacity = max(int(capacity), 1)
        self.path = path
        self.run_id = run_id
        self.hub = hub
        self.max_dumps = int(max_dumps)
        self.dumps = 0
        # ring slots: (deterministic record, wall-clock record)
        self._iters: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._events: collections.deque = collections.deque(
            maxlen=max(int(event_capacity), 1))
        # graftgauge hookup (attribute, not a constructor arg or
        # import — pulse stays gauge-free): when a MemorySampler is
        # wired, it points this at its deterministic_snapshot and the
        # per-iteration records gain a baseline-relative "memory" view
        # (docs/OBSERVABILITY.md "Capacity & memory"). Deltas, not
        # absolutes, so the bundle byte-stability contract holds: what
        # the RUN allocated is reproducible; what the process already
        # held is not.
        self.memory_provider: Optional[Any] = None

    # -- hub sink protocol ---------------------------------------------
    def on_iteration(self, ctx) -> None:
        memory = None
        if self.memory_provider is not None:
            try:
                memory = self.memory_provider()
            except Exception:  # observation must never break the ring
                memory = None
        det = {
            "iteration": int(ctx.iteration),
            "num_evals": float(ctx.num_evals),
            "best_loss": _finite(ctx.best_loss),
            # device counters ride along when the JSONL stream already
            # pulled them (hub.iteration); None otherwise — the
            # recorder never adds a transfer of its own
            "counters": list(ctx.counters) if ctx.counters else None,
            "memory": memory,
        }
        wall = {
            "iteration": int(ctx.iteration),
            "elapsed_s": float(ctx.elapsed),
            "evals_per_sec": float(ctx.evals_per_sec),
            "device_s": float(ctx.device_s),
            "host_s": float(ctx.host_s),
            "host_fraction": float(ctx.host_fraction),
        }
        self._iters.append((det, wall))

    # -- hub watcher protocol ------------------------------------------
    def on_event(self, event: Dict[str, Any]) -> None:
        """Observe one out-of-band hub event (fault/mesh/anomaly/pulse);
        a fault triggers an immediate dump — it may be the last thing
        this process ever does (watchdog abort)."""
        self._events.append(dict(event))
        if event.get("event") in _DUMP_TRIGGERS:
            self.dump(trigger={
                "reason": "fault",
                "kind": event.get("kind"),
                "iteration": event.get("iteration", 0),
            })
        elif (event.get("event") == "anomaly"
              and event.get("metric") in _DUMP_ANOMALY_METRICS):
            self.dump(trigger={
                "reason": "anomaly",
                "kind": event.get("metric"),
                "iteration": event.get("iteration", 0),
            })

    # ------------------------------------------------------------------
    def _trace_dict(self) -> Optional[Dict[str, Any]]:
        # graftledger trace context rides in from the hub (same ids the
        # JSONL stream stamps); deterministic given the request/run, so
        # it lives OUTSIDE wall and survives into the fingerprint
        trace = getattr(self.hub, "trace", None)
        if trace is None:
            return None
        try:
            return trace.to_dict()
        except Exception:
            return None

    def snapshot(self, trigger: Dict[str, Any]) -> Dict[str, Any]:
        """The bundle dict (see module docstring for the layout)."""
        det_iters = [d for d, _ in self._iters]
        wall_iters = [w for _, w in self._iters]
        events_det = []
        events_wall = []
        for e in self._events:
            events_det.append({
                "event": e.get("event"),
                "kind": e.get("kind", e.get("metric")),
                "iteration": e.get("iteration", 0),
            })
            events_wall.append(e)
        trig = dict(trigger)
        trig.setdefault("reason", "manual")
        return {
            "schema": BUNDLE_SCHEMA,
            "run_id": self.run_id,
            "trace": self._trace_dict(),
            "ring_capacity": self.capacity,
            "dump_seq": self.dumps + 1,
            "trigger": {k: trig[k] for k in sorted(trig)
                        if k != "wall" and trig[k] is not None},
            "iterations": det_iters,
            "events": events_det,
            "wall": {
                "iterations": wall_iters,
                "events": events_wall,
            },
        }

    def dump(self, *, trigger: Dict[str, Any],
             path: Optional[str] = None) -> Optional[str]:
        """Write the bundle; returns its path (None when pathless or
        over the dump budget). Never raises — the dump rides failure
        paths and must not mask the failure it documents."""
        target = path or self.path
        if target is None or self.dumps >= self.max_dumps:
            return None
        bundle = self.snapshot(trigger)
        try:
            d = os.path.dirname(target)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(target, "w") as f:
                json.dump(bundle, f, sort_keys=True, indent=1)
                f.write("\n")
        except OSError:
            return None
        self.dumps += 1
        if self.hub is not None:
            try:
                self.hub.pulse(
                    "bundle_dump",
                    iteration=int(bundle["trigger"].get("iteration", 0)),
                    reason=bundle["trigger"].get("reason"),
                    # "kind" would collide with pulse()'s own kind arg
                    trigger_kind=bundle["trigger"].get("kind"),
                    path=target,
                )
            except Exception:  # auditing must not mask the failure
                pass
        return target


# ---------------------------------------------------------------------------
# bundle consumers (tests, pulse_smoke, report tooling)
# ---------------------------------------------------------------------------


def deterministic_view(bundle: Dict[str, Any]) -> Dict[str, Any]:
    """The bundle minus its wall-clock subtree and dump ordinal — the
    part that is byte-stable across identical runs."""
    out = {k: v for k, v in bundle.items() if k not in ("wall", "dump_seq")}
    return out


def bundle_fingerprint(path: str) -> str:
    """sha256 over the canonical encoding of the deterministic view."""
    with open(path) as f:
        bundle = json.load(f)
    blob = json.dumps(deterministic_view(bundle), sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


_REQUIRED: Tuple[Tuple[str, type], ...] = (
    ("schema", str),
    ("run_id", str),
    ("ring_capacity", int),
    ("dump_seq", int),
    ("trigger", dict),
    ("iterations", list),
    ("events", list),
    ("wall", dict),
)


def validate_bundle(bundle: Any) -> List[str]:
    """Table-driven bundle check; returns violation strings (empty =
    valid) — the same hand-rolled style telemetry/schema.py uses."""
    if not isinstance(bundle, dict):
        return [f"bundle is {type(bundle).__name__}, expected object"]
    errors: List[str] = []
    if bundle.get("schema") != BUNDLE_SCHEMA:
        errors.append(
            f"schema is {bundle.get('schema')!r}, expected {BUNDLE_SCHEMA!r}")
    for name, typ in _REQUIRED:
        if name not in bundle:
            errors.append(f"missing field {name!r}")
        elif not isinstance(bundle[name], typ) or (
                typ is int and isinstance(bundle[name], bool)):
            errors.append(
                f"field {name!r} has type {type(bundle[name]).__name__}, "
                f"expected {typ.__name__}")
    for i, rec in enumerate(bundle.get("iterations") or []):
        if not isinstance(rec, dict) or "iteration" not in rec:
            errors.append(f"iterations[{i}]: not an iteration record")
    for i, ev in enumerate(bundle.get("events") or []):
        if not isinstance(ev, dict) or "event" not in ev:
            errors.append(f"events[{i}]: not an event record")
    wall = bundle.get("wall")
    if isinstance(wall, dict):
        for name in ("iterations", "events"):
            if not isinstance(wall.get(name), list):
                errors.append(f"wall.{name}: missing/not a list")
    return errors
