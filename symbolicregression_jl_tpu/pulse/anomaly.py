"""Rolling anomaly detection over per-iteration search metrics.

A telemetry-hub sink keeping exponentially-weighted mean/variance of
the per-iteration throughput and host-fraction signals, plus two
absolute rules (warm recompiles, invalid-candidate fraction). An
excursion emits an ``anomaly`` event through the hub and — via the
``on_anomaly`` callback — arms the rate-limited, budgeted profiler
capture (capture.py), so the evidence window opens AT the anomaly
instead of requiring a rerun under a hand-driven profiling script.

Watched metrics (docs/OBSERVABILITY.md has the threshold table):

- ``evals_per_sec`` — per-iteration rate (delta evals / delta wall
  time, not the cumulative average the progress bar shows): a retry
  storm, host stall, or degraded eval shape collapses it immediately.
  EWMA/z-score **in log space** (rate noise is multiplicative — a 10x
  collapse is the same sigma excursion at any absolute throughput),
  with a relative std floor; compile-bearing iterations are excluded
  from the rolling stats (they are legitimately 100-1000x slower, and
  the dedicated ``recompiles`` rule already covers unexpected ones).
- ``host_fraction`` — the monitor's host-work estimate; a sink or
  checkpoint path going quadratic drifts it up. EWMA/z-score.
- ``recompiles`` — any ``jaxpr_trace`` observed after the warmup
  window is anomalous (warm iterations must not retrace; the first
  iterations and the chunk-adaptation window compile legitimately).
- ``invalid_fraction`` — invalid candidates / candidates from the
  device counters, when the JSONL stream already pulled them (the
  detector never adds a device transfer of its own); a NaN storm
  spikes it. Absolute threshold. Under staged eval the structural
  unrescored-candidate NaN floor (screen_rows - rescore_rows,
  docs/PRECISION.md) is subtracted first — the rule watches the
  rescored candidates, which a genuine storm still poisons.
- ``live_bytes_growth`` — the graftgauge leak tripwire: live-array
  bytes strictly increasing over ``leak_window`` consecutive
  iterations by at least ``leak_min_bytes`` total (fed by
  gauge/sampler.py via :meth:`AnomalyDetector.observe_live_bytes`);
  the anomaly also triggers the flight-recorder bundle dump, so the
  memory snapshot lands on disk at the moment of detection.

Bit-neutral by construction: reads only host-side values the loop
already materialized, never touches state, keys, or options.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, Optional

__all__ = ["AnomalyDetector", "AnomalyThresholds"]


@dataclasses.dataclass
class AnomalyThresholds:
    """Detector tuning; the defaults are the zero-configuration
    contract CI's pulse-smoke pins (docs/OBSERVABILITY.md)."""

    zscore: float = 4.0           # |z| beyond this fires
    warmup: int = 5               # samples before z-rules may fire
    alpha: float = 0.3            # EWMA weight of the newest sample
    min_std_frac: float = 0.05    # std floor, as a fraction of |mean|
    invalid_fraction_max: float = 0.5
    cooldown: int = 8             # iterations between events per metric
    max_events: int = 32          # per-run event budget
    # graftstage staged-eval drift rule: relative tolerance on the
    # observed rescore fraction (rescore_rows / screen_rows from the
    # device counters) vs the configured Options.rescore_fraction. The
    # counters are exact static counts, so any drift beyond rounding
    # means the compiled program and the host-side config disagree —
    # a stale AOT executable or a mis-threaded knob.
    rescore_drift_tol: float = 0.2
    # graftgauge leak tripwire: live-array bytes (gauge/sampler.py)
    # growing STRICTLY monotonically over leak_window consecutive
    # iteration samples, by at least leak_min_bytes in total, fires a
    # live_bytes_growth anomaly (which also triggers the flight-
    # recorder bundle dump — recorder.py). A healthy search plateaus
    # after warmup (populations are fixed-size, loop temporaries are
    # freed functionally); unbroken growth means something is
    # accumulating references. The byte floor keeps small-object churn
    # (HoF growth toward its cap, python-side caches) below the rule.
    leak_window: int = 8
    leak_min_bytes: int = 1 << 20


class _Rolling:
    """Exponentially-weighted mean + variance of one scalar signal."""

    def __init__(self, alpha: float) -> None:
        self.alpha = float(alpha)
        self.mean: Optional[float] = None
        self.var = 0.0
        self.n = 0

    def zscore(self, x: float, min_std: float) -> Optional[float]:
        if self.mean is None:
            return None
        std = max(math.sqrt(max(self.var, 0.0)), min_std, 1e-12)
        return (x - self.mean) / std

    def update(self, x: float) -> None:
        self.n += 1
        if self.mean is None:
            self.mean = x
            self.var = 0.0
            return
        a = self.alpha
        delta = x - self.mean
        self.mean += a * delta
        # EW variance (West 1979 form): decays old spread, adds the
        # new sample's contribution around the pre-update mean
        self.var = (1.0 - a) * (self.var + a * delta * delta)


class AnomalyDetector:
    """Telemetry-hub sink; see module docstring."""

    def __init__(
        self,
        hub,
        *,
        thresholds: Optional[AnomalyThresholds] = None,
        on_anomaly: Optional[Callable[[str, int], None]] = None,
        expected_rescore_fraction: Optional[float] = None,
    ) -> None:
        self.hub = hub
        self.t = thresholds or AnomalyThresholds()
        self.on_anomaly = on_anomaly
        # None = staged eval off (or unknown config): drift rule dormant
        self.expected_rescore_fraction = expected_rescore_fraction
        self.events = 0
        self._roll: Dict[str, _Rolling] = {
            "evals_per_sec": _Rolling(self.t.alpha),
            "host_fraction": _Rolling(self.t.alpha),
        }
        self._cooldown_until: Dict[str, int] = {}
        self._last_evals: Optional[float] = None
        self._last_elapsed: Optional[float] = None
        self._last_traces: Optional[int] = None
        self._samples = 0
        # graftgauge leak tripwire state: the live-bytes value at the
        # start of the current strictly-increasing streak, the previous
        # sample, and the streak length
        self._leak_base: Optional[int] = None
        self._leak_prev: Optional[int] = None
        self._leak_streak = 0

    # ------------------------------------------------------------------
    def _fire(self, metric: str, iteration: int, **detail) -> None:
        if self.events >= self.t.max_events:
            return
        if iteration < self._cooldown_until.get(metric, 0):
            return
        self._cooldown_until[metric] = iteration + self.t.cooldown
        self.events += 1
        armed = False
        if self.on_anomaly is not None:
            try:
                armed = bool(self.on_anomaly(metric, iteration))
            except Exception:  # arming must never break detection
                armed = False
        self.hub.anomaly(metric, iteration=iteration,
                         armed_capture=armed or None, **detail)

    def _observe_z(self, metric: str, value: Optional[float],
                   iteration: int, *, log_space: bool = False) -> None:
        if value is None or not math.isfinite(value):
            return
        if log_space and value <= 0.0:
            return
        obs = math.log(value) if log_space else value
        roll = self._roll[metric]
        if roll.n >= self.t.warmup:
            min_std = abs(roll.mean or 0.0) * self.t.min_std_frac
            z = roll.zscore(obs, min_std)
            if z is not None and abs(z) > self.t.zscore:
                mean = (math.exp(roll.mean) if log_space and
                        roll.mean is not None else roll.mean)
                self._fire(
                    metric, iteration, value=round(value, 6),
                    mean=(None if mean is None else round(mean, 6)),
                    zscore=round(z, 3), threshold=self.t.zscore,
                )
        roll.update(obs)

    # -- graftgauge leak tripwire --------------------------------------
    def observe_live_bytes(self, iteration: int, live_bytes: int) -> None:
        """One per-iteration live-array byte sample (fed by
        gauge/sampler.py, not the hub sink protocol — the sampler runs
        as its own sink and hands the value here so the leak rule
        shares the detector's cooldown/budget/capture-arming plumbing).

        Fires ``live_bytes_growth`` after ``leak_window`` consecutive
        strictly-increasing samples whose total growth is at least
        ``leak_min_bytes``; any non-increase resets the streak."""
        b = int(live_bytes)
        if self._leak_prev is not None and b > self._leak_prev:
            self._leak_streak += 1
        else:
            self._leak_streak = 0
            self._leak_base = b
        if self._leak_base is None:
            self._leak_base = b
        self._leak_prev = b
        growth = b - self._leak_base
        if (self._leak_streak >= self.t.leak_window
                and growth >= self.t.leak_min_bytes):
            self._fire(
                "live_bytes_growth", int(iteration), value=b,
                growth_bytes=growth, window=self._leak_streak,
                threshold=self.t.leak_window,
            )

    # -- hub sink protocol ---------------------------------------------
    def on_iteration(self, ctx) -> None:
        it = int(ctx.iteration)
        self._samples += 1

        # per-iteration rate from the cumulative counters the loop
        # already computed (first sample has no delta; skip it)
        rate = None
        if self._last_evals is not None and self._last_elapsed is not None:
            dt = float(ctx.elapsed) - self._last_elapsed
            if dt > 0:
                rate = (float(ctx.num_evals) - self._last_evals) / dt
        self._last_evals = float(ctx.num_evals)
        self._last_elapsed = float(ctx.elapsed)
        traces = int(self.hub.compile_snapshot().get("traces", 0))
        compiled_this_iter = (self._last_traces is not None
                              and traces > self._last_traces)
        if not compiled_this_iter:
            # compile-bearing iterations are legitimately 100-1000x
            # slower — feeding them into the rolling rate stats would
            # inflate the variance past any real stall
            self._observe_z("evals_per_sec", rate, it, log_space=True)
        self._observe_z("host_fraction", float(ctx.host_fraction), it)

        # warm recompiles: absolute rule on the jax.monitoring trace
        # counter delta, past the warmup window (startup compiles and
        # the chunk-count adaptation retrace legitimately)
        if (compiled_this_iter and self._samples > self.t.warmup):
            self._fire(
                "recompiles", it,
                value=traces - self._last_traces, threshold=0,
            )
        self._last_traces = traces

        # invalid fraction from the device counters, when the stream
        # already fetched them (ctx.counters stays empty otherwise).
        # Under graftstage staged eval (docs/PRECISION.md) every
        # UNRESCORED candidate carries NaN cost by contract and the
        # device counter counts it invalid — subtract that structural
        # floor and measure the storm among rescored candidates, where
        # a genuine NaN storm still lands (NaN screens rank last but
        # top-k must still fill rescore_rows slots).
        worst = None
        for c in ctx.counters or ():
            if c and c.get("candidates"):
                inv = c.get("invalid", 0)
                cand = c["candidates"]
                unrescored = max(
                    0, c.get("screen_rows", 0) - c.get("rescore_rows", 0))
                if unrescored:
                    inv = max(0, inv - unrescored)
                    cand = max(1, cand - unrescored)
                frac = inv / cand
                worst = frac if worst is None else max(worst, frac)
        if worst is not None and worst > self.t.invalid_fraction_max:
            self._fire(
                "invalid_fraction", it, value=round(worst, 6),
                threshold=self.t.invalid_fraction_max,
            )

        # graftstage rescore-fraction drift (docs/PRECISION.md): the
        # staged screen/rescore counts are static per compiled program,
        # so the observed ratio should match the configured fraction up
        # to per-launch ceil rounding; past rescore_drift_tol the
        # program serving this search was built from different knobs.
        expect = self.expected_rescore_fraction
        if expect:
            worst_drift = None
            observed = None
            for c in ctx.counters or ():
                if c and c.get("screen_rows"):
                    frac = c.get("rescore_rows", 0) / c["screen_rows"]
                    drift = abs(frac - expect) / expect
                    if worst_drift is None or drift > worst_drift:
                        worst_drift, observed = drift, frac
            if worst_drift is not None and worst_drift > self.t.rescore_drift_tol:
                self._fire(
                    "rescore_fraction_drift", it,
                    value=round(observed, 6), expected=round(expect, 6),
                    threshold=self.t.rescore_drift_tol,
                )
