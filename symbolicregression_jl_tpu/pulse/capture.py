"""Triggered profiler capture: bounded jax.profiler trace windows.

The ``sr:iteration`` / ``sr:host:*`` spans (telemetry/spans.py) are
always on but only matter while a trace is being captured; this module
is the thing that captures one — programmatically, from inside the
running search, at the moment something looks wrong:

- the anomaly detector arms a window when a watched metric excurses;
- ``RuntimeOptions(pulse_trace_on=True)`` arms one at the first
  iteration (and graftserve's ``submit(pulse_trace=True)`` sets it for
  one request);
- SIGUSR2 (``SignalArm``) arms one on demand against a live process.

A window spans ``window_iterations`` search iterations and is bounded
two ways: at most ``max_captures`` per run and at least
``min_interval_s`` between windows — a flapping metric cannot turn the
run into one long profiling session. Every transition is audited as a
``pulse`` event (capture_armed / capture_start / capture_stop /
capture_failed) so the stream explains every trace directory on disk.

Trace output lands under ``<out_dir>/pulse_traces/captureNN/`` in the
standard jax layout (xplane protobufs; plus a ``perfetto_trace.json.gz``
when ``perfetto=True``, the default).
"""

from __future__ import annotations

import glob
import os
import signal
import threading
import time
from typing import List, Optional

__all__ = ["TraceCapture", "SignalArm"]


class TraceCapture:
    """One run's budgeted profiler-capture controller; see module
    docstring. Driven by the search loop at iteration boundaries
    (``maybe_start`` before the iteration's device work, ``maybe_stop``
    after it), so a window always covers whole iterations."""

    def __init__(
        self,
        out_dir: str,
        *,
        hub=None,
        window_iterations: int = 2,
        max_captures: int = 2,
        min_interval_s: float = 30.0,
        perfetto: bool = True,
        clock=time.monotonic,
    ) -> None:
        self.root = os.path.join(out_dir, "pulse_traces")
        self.hub = hub
        self.window_iterations = max(int(window_iterations), 1)
        self.max_captures = max(int(max_captures), 0)
        self.min_interval_s = float(min_interval_s)
        self.perfetto = bool(perfetto)
        self._clock = clock
        self._armed_reason: Optional[str] = None
        self._started_at: Optional[int] = None
        self._dir: Optional[str] = None
        self._last_stop_t: Optional[float] = None
        self.captures = 0
        self.disabled = False  # a failing profiler disables the rest

    # ------------------------------------------------------------------
    def _pulse(self, kind: str, iteration: int, **detail) -> None:
        if self.hub is None:
            return
        try:
            self.hub.pulse(kind, iteration=iteration, **detail)
        except Exception:  # auditing must not break the capture
            pass

    @property
    def active(self) -> bool:
        return self._started_at is not None

    def arm(self, reason: str, iteration: int = 0) -> bool:
        """Request a capture window; returns True when armed. Denied
        (quietly — the caller may be a signal-driven retry loop) when
        already armed/active, over budget, inside the rate-limit
        window, or after a profiler failure."""
        if self.disabled or self._armed_reason is not None or self.active:
            return False
        if self.captures >= self.max_captures:
            return False
        if (self._last_stop_t is not None
                and self._clock() - self._last_stop_t < self.min_interval_s):
            return False
        self._armed_reason = str(reason)
        self._pulse("capture_armed", iteration, reason=self._armed_reason)
        return True

    def maybe_start(self, iteration: int) -> bool:
        """Open the window if one is armed (loop calls this right
        before the iteration's device work)."""
        if self._armed_reason is None or self.active or self.disabled:
            return False
        d = os.path.join(self.root, f"capture{self.captures + 1:02d}")
        try:
            os.makedirs(d, exist_ok=True)
            import jax.profiler

            jax.profiler.start_trace(
                d, create_perfetto_trace=self.perfetto)
        except Exception as e:
            self.disabled = True
            reason, self._armed_reason = self._armed_reason, None
            self._pulse(
                "capture_failed", iteration, reason=reason,
                error=f"{type(e).__name__}: {e}"[:200],
            )
            return False
        self._started_at = int(iteration)
        self._dir = d
        self._pulse("capture_start", iteration,
                    reason=self._armed_reason, trace_dir=d)
        return True

    def maybe_stop(self, iteration: int, *, force: bool = False) -> bool:
        """Close the window once it has covered ``window_iterations``
        completed iterations (loop calls this after each boundary);
        ``force`` closes it immediately (end of run)."""
        if not self.active:
            return False
        covered = int(iteration) - (self._started_at or 0) + 1
        if not force and covered < self.window_iterations:
            return False
        trace_dir = self._dir
        reason = self._armed_reason
        self._armed_reason = None
        self._started_at = None
        self._dir = None
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception as e:
            self.disabled = True
            self._pulse(
                "capture_failed", iteration, reason=reason,
                error=f"{type(e).__name__}: {e}"[:200],
            )
            return False
        self.captures += 1
        self._last_stop_t = self._clock()
        files = self.trace_files(trace_dir)
        self._pulse(
            "capture_stop", iteration, reason=reason,
            trace_dir=trace_dir, iterations=max(covered, 0),
            files=len(files),
            bytes=sum(os.path.getsize(f) for f in files),
        )
        return True

    def close(self, iteration: int = 0) -> None:
        """Force-stop any open window (run teardown): an abandoned
        ``start_trace`` would leave the profiler session open and the
        trace files unwritten."""
        self.maybe_stop(iteration, force=True)

    @staticmethod
    def trace_files(trace_dir: Optional[str]) -> List[str]:
        """Every file the profiler wrote under one capture directory."""
        if not trace_dir:
            return []
        return sorted(
            p for p in glob.glob(
                os.path.join(trace_dir, "**", "*"), recursive=True)
            if os.path.isfile(p)
        )


class SignalArm:
    """SIGUSR2 → "arm a capture" flag for a live process.

    GL007 discipline (shield/signals.py is the reference): the handler
    body only sets a ``threading.Event`` — no jax calls, no IO. The
    search loop polls ``consume()`` at iteration boundaries and does
    the actual arming there. Install is main-thread-only (a Python
    limitation); a worker-thread search simply runs without the signal
    surface — the other arming paths still work.
    """

    def __init__(self, signum: int = signal.SIGUSR2) -> None:
        self.signum = signum
        self._flag = threading.Event()
        self._prev = None
        self.installed = False

    def _on_signal(self, signum, frame) -> None:
        self._flag.set()

    def install(self) -> "SignalArm":
        if self.installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self
        try:
            self._prev = signal.signal(self.signum, self._on_signal)
            self.installed = True
        except (ValueError, OSError, AttributeError):
            self._prev = None
        return self

    def uninstall(self) -> None:
        if not self.installed:
            return
        if threading.current_thread() is threading.main_thread():
            try:
                signal.signal(self.signum, self._prev)
            except (ValueError, OSError, TypeError):
                pass
        self.installed = False
        self._flag.clear()

    def consume(self) -> bool:
        """True once per delivered signal."""
        if self._flag.is_set():
            self._flag.clear()
            return True
        return False
