"""graftpulse: active diagnostics on top of the graftscope stream.

graftscope (telemetry/) made every run *emit* schema-versioned
telemetry; graftpulse makes the emitting process hold the evidence an
operator needs the moment something goes wrong, instead of an exit
code and a log tail:

- :class:`FlightRecorder` (recorder.py) — a bounded in-memory ring of
  the last K iterations' device counters + host timings + recent
  out-of-band events, registered as a telemetry-hub sink/watcher and
  dumped as a self-contained ``graftpulse.bundle.v1`` JSON bundle when
  a fault fires (watchdog timeout, quarantine, injection) or the run
  exits nonzero.
- :class:`AnomalyDetector` (anomaly.py) — rolling EWMA/z-score over
  per-iteration evals/s, host_fraction, recompile count and
  invalid-fraction; emits ``anomaly`` events and arms a rate-limited,
  budgeted profiler capture.
- :class:`TraceCapture` + :class:`SignalArm` (capture.py) —
  programmatic ``jax.profiler`` trace windows (the ``sr:iteration`` /
  ``sr:host:*`` spans' consumer), armed by SIGUSR2, a
  ``RuntimeOptions(pulse_trace_on=...)`` knob, a serve request flag,
  or the detector.
- :class:`PromText` (metrics.py) — the Prometheus text-exposition
  builder behind graftserve's ``/metrics`` endpoint.

Everything here is observability-only and bit-neutral to the search:
host-side reads of values the loop already materialized, zero extra
device dispatches or transfers (pinned by tests/test_pulse.py's on/off
A/B, the same contract graftscope carries). See docs/OBSERVABILITY.md.
"""

from .anomaly import AnomalyDetector, AnomalyThresholds
from .capture import SignalArm, TraceCapture
from .metrics import PromText
from .recorder import (
    BUNDLE_SCHEMA,
    FlightRecorder,
    bundle_fingerprint,
    deterministic_view,
    validate_bundle,
)

__all__ = [
    "AnomalyDetector",
    "AnomalyThresholds",
    "BUNDLE_SCHEMA",
    "FlightRecorder",
    "PromText",
    "SignalArm",
    "TraceCapture",
    "bundle_fingerprint",
    "deterministic_view",
    "validate_bundle",
]
