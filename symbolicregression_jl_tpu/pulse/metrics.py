"""Prometheus text-exposition builder for the live metrics surface.

graftserve's ``/metrics`` endpoint (serve/metrics.py) renders its
gauges through this tiny builder rather than depending on the
``prometheus_client`` package (not in the image, and overkill for a
read-only exposition of a dozen gauges). The output follows the
text format v0.0.4: one ``# HELP`` / ``# TYPE`` pair per metric family
(emitted once, on first sample), then one sample line per label set.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

__all__ = ["PromText", "histogram_quantile"]


def histogram_quantile(le_bounds: Sequence[float],
                       counts: Sequence[int],
                       q: float) -> Optional[float]:
    """Bucket-upper-bound quantile estimate over a pre-bucketed
    histogram (``counts`` may carry the extra +Inf slot past
    ``le_bounds``). Returns the upper bound of the bucket containing
    the q-th sample — the same coarse-but-honest estimate a
    ``histogram_quantile()`` PromQL query makes — or None while empty.
    Samples in the +Inf bucket report the last finite bound (a floor,
    not a fabricated extrapolation)."""
    total = sum(int(n) for n in counts)
    if total <= 0 or not le_bounds:
        return None
    target = max(min(float(q), 1.0), 0.0) * total
    cum = 0
    for i, n in enumerate(counts):
        cum += int(n)
        if cum >= target and cum > 0:
            return float(le_bounds[min(i, len(le_bounds) - 1)])
    return float(le_bounds[-1])


def _escape_label(value: str) -> str:
    return (str(value)
            .replace("\\", "\\\\")
            .replace("\n", "\\n")
            .replace('"', '\\"'))


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


class PromText:
    """Accumulate samples, then ``render()`` the exposition body."""

    def __init__(self, prefix: str = "graftserve") -> None:
        self.prefix = prefix
        self._lines: List[str] = []
        self._seen_meta: Dict[str, str] = {}  # family -> declared type

    def _sample(self, name: str, mtype: str, help_text: str,
                value, labels: Optional[Dict[str, str]]) -> None:
        family = f"{self.prefix}_{name}" if self.prefix else name
        if family not in self._seen_meta:
            self._seen_meta[family] = mtype
            self._lines.append(f"# HELP {family} {_escape_help(help_text)}")
            self._lines.append(f"# TYPE {family} {mtype}")
        label_str = ""
        if labels:
            pairs = ",".join(
                f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items()))
            label_str = "{" + pairs + "}"
        try:
            v = float(value)
        except (TypeError, ValueError):
            v = float("nan")
        # integers render without a trailing .0 (matches common
        # exporters; keeps counters diff-friendly)
        body = repr(int(v)) if v == int(v) and abs(v) < 1e15 else repr(v)
        self._lines.append(f"{family}{label_str} {body}")

    def gauge(self, name: str, value, help_text: str = "",
              labels: Optional[Dict[str, str]] = None) -> "PromText":
        self._sample(name, "gauge", help_text, value, labels)
        return self

    def counter(self, name: str, value, help_text: str = "",
                labels: Optional[Dict[str, str]] = None) -> "PromText":
        self._sample(name, "counter", help_text, value, labels)
        return self

    def histogram(self, name: str, le_bounds, bucket_counts, sum_value,
                  help_text: str = "",
                  labels: Optional[Dict[str, str]] = None) -> "PromText":
        """One pre-bucketed histogram sample set (graftledger's
        log-bucketed iteration latencies): ``bucket_counts`` has one
        extra slot past ``le_bounds`` for the +Inf bucket; buckets
        render CUMULATIVE per the exposition format, plus the
        ``_count`` / ``_sum`` series."""
        family = f"{self.prefix}_{name}" if self.prefix else name
        if family not in self._seen_meta:
            self._seen_meta[family] = "histogram"
            self._lines.append(f"# HELP {family} {_escape_help(help_text)}")
            self._lines.append(f"# TYPE {family} histogram")
        base = dict(labels or {})

        def label_str(extra: Dict[str, str]) -> str:
            pairs = ",".join(
                f'{k}="{_escape_label(v)}"'
                for k, v in sorted({**base, **extra}.items()))
            return "{" + pairs + "}" if pairs else ""

        cum = 0
        for le, n in zip(le_bounds, bucket_counts):
            cum += int(n)
            self._lines.append(
                f"{family}_bucket{label_str({'le': repr(float(le))})} {cum}")
        cum += int(bucket_counts[len(le_bounds)]) \
            if len(bucket_counts) > len(le_bounds) else 0
        self._lines.append(
            f"{family}_bucket{label_str({'le': '+Inf'})} {cum}")
        self._lines.append(f"{family}_count{label_str({})} {cum}")
        self._lines.append(
            f"{family}_sum{label_str({})} {float(sum_value)!r}")
        return self

    def render(self) -> str:
        return "\n".join(self._lines) + ("\n" if self._lines else "")
